"""TPU kernels for the fragment hot loops.

The reference's performance-critical inner loops are the per-container
word loops in roaring/roaring.go:3078-4414 (AND/OR/XOR/ANDNOT + popcount,
e.g. ``intersectionCountBitmapBitmap`` roaring.go:568) and the TopN row
recount (fragment.go:459-498, 1568-1700).  On TPU those become:

* **The MXU gram path** (:func:`pair_gram`): ``popcount(a & b)`` is the
  dot product of the two rows viewed as 0/1 vectors, so a whole batch of
  ``Count(op(Row, Row))`` queries collapses into ONE scan of the index
  that unpacks each word block to int8 and accumulates a gram matrix
  ``G[i, j] = |row_i & row_j|`` on the systolic array.  Every pair op
  reduces to gram entries: ``|a|b| = G[aa]+G[bb]-G[ab]``,
  ``|a\\b| = G[aa]-G[ab]``, ``|a^b| = G[aa]+G[bb]-2G[ab]``.  Measured on
  v5e (10.7e9-bit index, B=1024): 21.6 ms/launch for all 64x64 pairs
  with the fused-unpack Pallas kernel (36 ms for the XLA scan, 918 ms
  for the per-query gather+popcount scan) — the MXU turns 2*B row reads
  into one index read, and the Pallas variant keeps the 32x int8
  expansion in VMEM instead of HBM.
* **Fused XLA scans** for per-row popcounts (TopN) and everything else:
  measured ~297 GB/s on v5e at the 10.7e9-bit shape once the relay
  round trip is amortized over 24 pipelined launches (bench.py r05).
  Earlier rounds reported 103-107 GB/s and called it a VPU popcount
  ceiling — that figure was 6-or-fewer launches absorbing a ~64 ms
  relay RTT into the per-launch average, not a kernel property; the
  corrected number sits at ~36% of v5e's 819 GB/s HBM stream, so the
  scan is HBM/fusion-bound, with headroom that doesn't matter
  architecturally (see maintained counts below).  Pallas row-scan
  variants measured at parity, so they stay OFF by default
  (``PILOSA_TPU_PALLAS=1`` re-enables the row-scan kernels for
  hardware where the balance differs; they compile on real TPU —
  (8-shard, full-row, word-block) tiles — and validate under interpret
  mode in tests).  Architecturally the cold scan is also mostly
  retired: unfiltered TopN serves from counts MAINTAINED across writes
  (core/fragment.py), so the scan only runs on stack rebuilds.  The earlier scalar-prefetch pair-count kernels were
  REMOVED: their one-row blocks violate the TPU (8, 128) tiling rule
  outright, and the gram path supersedes them.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from functools import lru_cache, partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.sharding import NamedSharding, PartitionSpec as P

from pilosa_tpu.compat import shard_map

from pilosa_tpu.obs import devledger, qprofile
from pilosa_tpu.obs.stats import MemStatsClient
from pilosa_tpu.ops.bitops import pow2_pad_len

# Device cost ledger sites: every batched-kernel dispatch funnels through
# _note_dispatch, which claims the thread's XLA compile events and books
# the launch — BSI batched lanes report under their own site so the ledger
# splits standard-row vs BSI kernel costs.
_DL_KERNELS = devledger.site("ops.kernels")
_DL_BSI = devledger.site("ops.bsi")

logger = logging.getLogger(__name__)

_OPS = {
    "intersect": lambda a, b: a & b,
    "union": lambda a, b: a | b,
    "difference": lambda a, b: a & ~b,
    "xor": lambda a, b: a ^ b,
}


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def pallas_supported() -> bool:
    """Whether dispatch should try the Pallas kernels.

    Default OFF everywhere: measured on a real v5e, XLA's fused
    popcount+reduce outruns the hand-written streaming kernels (154 vs
    106 GB/s row scan), and the scalar-prefetch pair-count kernel's
    (1, 1, W) blocks violate the TPU (8, 128) tiling rule outright.  The
    MXU gram path (:func:`pair_gram`) is the serving kernel instead.
    ``PILOSA_TPU_PALLAS=1`` re-enables Pallas dispatch for hardware where
    the balance differs; on CPU the kernels always run in tests via
    ``interpret=True`` when called directly."""
    return (
        os.environ.get("PILOSA_TPU_PALLAS") == "1"
        and jax.default_backend() == "tpu"
    )


def _word_block(w: int, cap: int) -> int:
    """Largest power-of-two-ish divisor of ``w`` not exceeding ``cap``."""
    wb = min(w, cap)
    while w % wb:
        wb //= 2
    return max(wb, 1)


# ---------------------------------------------------------------------------
# Batched pair count: Count(op(Row(ra[i]), Row(rb[i]))) for i in [0, B)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("op",))
def pair_count_batched_xla(
    bits: jax.Array, ras: jax.Array, rbs: jax.Array, *, op: str = "intersect"
) -> jax.Array:
    """Fallback: device-side scan over the query batch (not vmap, which
    would materialize the [B, S, W] gather). Returns int32[B, S] per-shard
    partials like the Pallas kernel."""

    def body(_, q):
        ra, rb = q
        words = _OPS[op](bits[:, ra], bits[:, rb])
        return None, jnp.sum(
            lax.population_count(words).astype(jnp.int32), axis=-1
        )

    _, counts = lax.scan(body, None, (ras, rbs))
    return counts


_pallas_ok: bool | None = None

# Count of silent Pallas→XLA demotions after the backend was proven good
# (an established _pallas_ok=True): device OOM or a miscompiled shape
# would otherwise become invisible performance degradation.  Surfaced via
# diagnostics (pallas_fallbacks) so operators can see repeated failures.
# Dispatch runs on the HTTP request pool, so the counter is locked.
_pallas_fallbacks: int = 0
_PALLAS_FALLBACK_LOG_EVERY = 10
_fallback_lock = threading.Lock()

# Process-wide kernel/dispatch telemetry, rendered as ``pilosa_kernel_*``
# by /metrics and snapshotted into /debug/vars and bench records.  Lives
# here rather than on the holder because dispatch decisions are made in
# this module, below any holder plumbing.
kernel_stats = MemStatsClient()

_dispatch_lock = threading.Lock()
_seen_programs: set = set()
_MAX_SEEN_PROGRAMS = 4096


def pallas_fallback_count() -> int:
    with _fallback_lock:
        return _pallas_fallbacks


def _note_pallas_fallback(exc: Exception) -> None:
    global _pallas_fallbacks
    with _fallback_lock:
        _pallas_fallbacks += 1
        n = _pallas_fallbacks
    kernel_stats.count("kernel_pallas_fallbacks")
    if n % _PALLAS_FALLBACK_LOG_EVERY == 1:
        logger.warning(
            "pallas kernel demoted to XLA fallback (#%d): %r",
            n,
            exc,
        )


def _fn_kernel_name(fn) -> str:
    """Human kernel name from a dispatch target (lane/builder suffixes
    stripped so pallas/xla variants of one kernel share a name)."""
    name = getattr(fn, "__name__", None)
    if name is None:
        name = getattr(getattr(fn, "func", None), "__name__", None) or "kernel"
    for suffix in ("_sharded_fn", "_pallas", "_xla"):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
    return name.lstrip("_")


def _shape_sig(args) -> tuple:
    return tuple(
        tuple(a.shape) for a in args if getattr(a, "shape", None) is not None
    )


def _note_dispatch(
    kernel: str,
    lane: str,
    *,
    wall: float | None = None,
    args=(),
    demoted: bool = False,
    padded_bytes: int = 0,
    useful_bytes: int = 0,
    extra: dict | None = None,
    extra_tags: tuple = (),
    dl_site=None,
) -> None:
    """Record one kernel dispatch: tagged counters/timings into
    ``kernel_stats`` plus a per-kernel record into the active query
    profile.  ``wall`` is launch wall time — device work may still be in
    flight unless the caller synchronized.  The jit compile-cache
    hit/miss is a proxy: first sight of (kernel, lane, arg shapes) in
    this process, mirroring XLA's shape-keyed jit cache.  ``extra``
    merges lane-specific labels into the profile record and
    ``extra_tags`` onto the dispatch counter (bounded cardinality is the
    caller's responsibility)."""
    key = (kernel, lane, _shape_sig(args))
    with _dispatch_lock:
        miss = key not in _seen_programs
        if miss and len(_seen_programs) < _MAX_SEEN_PROGRAMS:
            _seen_programs.add(key)
    if lane != "host":
        # Ledger booking: the jit call already returned on this thread, so
        # any XLA compiles it triggered sit in the thread stash — claim
        # them under this site, and book the launch + identity.
        site = dl_site or _DL_KERNELS
        site.track_key(key)
        site.claim(sig=f"{kernel}/{lane}:{key[2]}")
        site.record_launch(wall or 0.0)
    tagged = kernel_stats.with_tags(
        f"kernel:{kernel}", f"lane:{lane}", *extra_tags
    )
    tagged.count("kernel_dispatch")
    kernel_stats.count(
        "kernel_compile_misses" if miss else "kernel_compile_hits"
    )
    if demoted:
        tagged.count("kernel_demotions")
    if padded_bytes:
        tagged.count("kernel_padded_bytes", int(padded_bytes))
        tagged.count("kernel_useful_bytes", int(useful_bytes))
    if wall is not None:
        tagged.timing("kernel_dispatch", wall)
    rec: dict = {
        "kernel": kernel,
        "lane": lane,
        "jit_cache": "miss" if miss else "hit",
    }
    if wall is not None:
        rec["wall_ms"] = round(wall * 1e3, 3)
    if demoted:
        rec["demoted"] = True
    if padded_bytes:
        rec["padded_bytes"] = int(padded_bytes)
        rec["useful_bytes"] = int(useful_bytes)
    if extra:
        rec.update(extra)
    qprofile.record_kernel(**rec)


def note_bsi_dispatch(
    kernel: str,
    *,
    wall: float,
    args,
    depth: int,
    q_bucket: int,
    q_useful: int,
    lane: str = "xla",
) -> None:
    """BSI batched-lane dispatch: same pipeline as :func:`_note_dispatch`
    but labelled with the lane's (depth, Q-bucket) compile key and the
    padded-vs-useful query split, so the shape-keyed program cache the
    batched kernels compile against is observable in ``?profile=true``
    records and ``pilosa_kernel_*`` metrics."""
    _note_dispatch(
        kernel,
        lane,
        wall=wall,
        args=args,
        extra={"depth": int(depth), "qBucket": int(q_bucket),
               "qUseful": int(q_useful)},
        extra_tags=(f"depth:{depth}", f"qbucket:{q_bucket}"),
        dl_site=_DL_BSI,
    )
    if q_bucket > q_useful:
        # pow2 Q padding: queries, scaled to the per-query input bytes
        tagged = kernel_stats.with_tags(f"kernel:{kernel}")
        tagged.count("kernel_padded_queries", int(q_bucket - q_useful))
        tagged.count("kernel_useful_queries", int(q_useful))
    else:
        kernel_stats.with_tags(f"kernel:{kernel}").count(
            "kernel_useful_queries", int(q_useful)
        )


def note_transfer(nbytes: int, direction: str, dl_site=None) -> None:
    """Count host<->device traffic (``direction``: "h2d" | "d2h").
    ``dl_site`` routes the ledger booking to the caller's registered site
    (executor stack builds, fragment syncs); defaults to ops.kernels."""
    if nbytes:
        kernel_stats.with_tags(f"direction:{direction}").count(
            "kernel_transfer_bytes", int(nbytes)
        )
        qprofile.incr(f"transfer_{direction}_bytes", int(nbytes))
        site = dl_site or devledger.active_window_site() or _DL_KERNELS
        site.record_transfer(int(nbytes), direction)


def note_pad(kernel: str, padded_bytes: int, useful_bytes: int) -> None:
    """Padding accounting for pow2 batch/gather padding (callers that
    know the padded and useful extents but dispatch elsewhere)."""
    tagged = kernel_stats.with_tags(f"kernel:{kernel}")
    tagged.count("kernel_padded_bytes", int(padded_bytes))
    tagged.count("kernel_useful_bytes", int(useful_bytes))


def _pull(out) -> np.ndarray:
    """Materialize a device result on the host, counting the d2h bytes."""
    arr = np.asarray(out)
    note_transfer(arr.nbytes, "d2h")
    return arr


def record_host_op(kernel: str) -> None:
    """Executor host-path ops (python/numpy row materialization) report
    through the same telemetry under lane=host."""
    _note_dispatch(kernel, "host")


def telemetry_snapshot() -> dict:
    """JSON-safe kernel-telemetry rollup for /debug/vars, bench records
    and tests: dispatch-lane counts, compile-cache proxy, transfer
    bytes, pallas gate states."""
    snap = kernel_stats.snapshot()
    lanes: dict[str, int] = {}
    transfers: dict[str, int] = {}
    compile_cache = {"hits": 0, "misses": 0}
    for label, v in snap["counters"].items():
        name, _, tagstr = label.partition("{")
        tags = dict(
            t.split(":", 1) for t in tagstr.rstrip("}").split(",") if ":" in t
        )
        if name == "kernel_dispatch":
            lane = tags.get("lane", "?")
            lanes[lane] = lanes.get(lane, 0) + int(v)
        elif name == "kernel_transfer_bytes":
            d = tags.get("direction", "?")
            transfers[d] = transfers.get(d, 0) + int(v)
        elif name == "kernel_compile_hits":
            compile_cache["hits"] += int(v)
        elif name == "kernel_compile_misses":
            compile_cache["misses"] += int(v)
    return {
        "pallas_supported": pallas_supported(),
        "pallas_ok": _pallas_ok,
        "pallas_fallbacks": pallas_fallback_count(),
        "gram_gates": {
            "self": {
                "ok": _self_gram_gate.ok,
                "fails": _self_gram_gate.fails,
            },
            "cross": {
                "ok": _cross_gram_gate.ok,
                "fails": _cross_gram_gate.fails,
            },
        },
        "dispatch_lanes": lanes,
        "compile_cache": compile_cache,
        "transfer_bytes": transfers,
        "counters": snap["counters"],
    }


def _multi_device(x) -> bool:
    """True when ``x`` is laid out across more than one device.

    pallas_call is not sharding-aware: feeding it a NamedSharding'd stack
    would either fail or make XLA replicate the full bitmap onto every
    device — exactly the materialization the mesh layout avoids.  Arrays
    sharded over a leading ``shards``-style mesh axis take the shard_map
    path below (per-device Pallas on TPU); anything else multi-device
    keeps the fused-XLA path, whose jnp ops partition over the mesh and
    reduce over ICI."""
    try:
        return len(x.sharding.device_set) > 1
    except AttributeError:
        return False


def shards_axis_of(x):
    """(mesh, axis_name) when ``x`` is NamedSharding'd with ONLY its
    leading dimension split over one mesh axis — the serving-stack layout
    (executor field stacks: P("shards", None, ...)).  None otherwise."""
    s = getattr(x, "sharding", None)
    if not isinstance(s, NamedSharding) or len(s.device_set) <= 1:
        return None
    spec = tuple(s.spec)
    if not spec or spec[0] is None:
        return None
    first = spec[0]
    if isinstance(first, (tuple, list)):
        if len(first) != 1:
            return None
        first = first[0]
    if not isinstance(first, str):
        return None
    if any(p is not None for p in spec[1:]):
        return None
    return s.mesh, first


@lru_cache(maxsize=64)
def _pair_count_sharded_fn(mesh, axis, op, two_tensor):
    """jit(shard_map) answering a pair-count batch over a shards-sharded
    stack: each device runs the single-device scan on its local shard
    block; per-shard partials concatenate back along the shard axis —
    the ICI replacement for the reference's per-node mapReduce fan-out
    (executor.go:2454-2611)."""
    if two_tensor:
        local = partial(pair_count_two_batched_xla, op=op)
        in_specs = (P(axis, None, None), P(axis, None, None), P(None), P(None))
    else:
        local = partial(pair_count_batched_xla, op=op)
        in_specs = (P(axis, None, None), P(None), P(None))
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(None, axis),
        )
    )


@lru_cache(maxsize=64)
def _row_counts_mesh_fn(mesh, axis, use_pallas, in_program_reduce):
    """jit(shard_map) row popcounts over a shards-sharded stack — per-
    shard int32[S, R] partials along the mesh axis for a host-side sum,
    or an in-program psum reduce to a replicated int32[R] for
    process-spanning meshes (XLA local only there; same two modes as
    _gram_mesh_fn)."""
    if in_program_reduce:
        local = lambda b: lax.psum(row_counts_xla(b), axis)
        out_specs = P(None)
    else:
        local = (
            row_counts_per_shard_pallas
            if use_pallas
            else row_counts_per_shard_xla
        )
        out_specs = P(axis, None)
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None, None),),
            out_specs=out_specs,
        )
    )


def _row_counts_sharded_fn(mesh, axis, use_pallas):
    return _row_counts_mesh_fn(mesh, axis, use_pallas, False)


def _run_sharded(builder, builder_args, call_args) -> jax.Array:
    """Invoke a sharded kernel with the same Pallas→XLA degradation
    contract as _try_pallas: a Pallas compile/runtime failure demotes and
    re-answers with the XLA local kernel instead of failing the query.
    Builders take a trailing ``use_pallas`` flag; XLA-only kernels call
    their jit(shard_map) builder directly instead."""
    global _pallas_ok
    kname = _fn_kernel_name(builder)
    use_pallas = pallas_supported() and _pallas_ok is not False
    if use_pallas:
        try:
            t0 = time.perf_counter()
            out = builder(*builder_args, True)(*call_args)
            if _pallas_ok is None:
                jax.block_until_ready(out)
                _pallas_ok = True
            _note_dispatch(
                kname, "pallas", wall=time.perf_counter() - t0, args=call_args
            )
            return out
        except Exception as exc:
            # match _try_pallas: an established True flag survives a
            # one-off shape failure; only an unproven backend demotes
            if _pallas_ok is None:
                _pallas_ok = False
            else:
                _note_pallas_fallback(exc)
    t0 = time.perf_counter()
    out = builder(*builder_args, False)(*call_args)
    _note_dispatch(
        kname,
        "xla",
        wall=time.perf_counter() - t0,
        args=call_args,
        demoted=use_pallas,
    )
    return out


def _try_pallas(fn, fallback, *args, **kwargs) -> jax.Array:
    """Run the Pallas kernel, falling back to fused XLA on ANY failure.
    The permanent flag only decides whether to *try* Pallas next time —
    one bad shape/op must never fail a query that the fallback can
    answer."""
    global _pallas_ok
    if (
        _pallas_ok is False
        or not pallas_supported()
        or any(_multi_device(a) for a in args)
    ):
        t0 = time.perf_counter()
        out = fallback(*args, **kwargs)
        _note_dispatch(
            _fn_kernel_name(fallback),
            "xla",
            wall=time.perf_counter() - t0,
            args=args,
        )
        return out
    try:
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        if _pallas_ok is None:
            jax.block_until_ready(out)
            _pallas_ok = True
        _note_dispatch(
            _fn_kernel_name(fn),
            "pallas",
            wall=time.perf_counter() - t0,
            args=args,
        )
        return out
    except Exception as exc:
        if _pallas_ok is None:
            _pallas_ok = False
        else:
            _note_pallas_fallback(exc)
        t0 = time.perf_counter()
        out = fallback(*args, **kwargs)
        _note_dispatch(
            _fn_kernel_name(fallback),
            "xla",
            wall=time.perf_counter() - t0,
            args=args,
            demoted=True,
        )
        return out


def pair_count_batched(
    bits: jax.Array, ras: jax.Array, rbs: jax.Array, *, op: str = "intersect"
):
    """Pair counts for a query batch.  Local stacks return device
    ``int32[B, S]`` per-shard partials (callers sum host-side); on a
    PROCESS-SPANNING mesh those partials are not host addressable, so
    the reduce happens in-program — chunked psum with (hi, lo)
    carry-save past int32 — and the result is replicated
    ``np.int64[B]`` totals (already summed over shards)."""
    m = shards_axis_of(bits)
    if m is not None:
        mesh, axis = m
        if mesh_spans_processes(mesh):
            _, _, W = bits.shape
            chunk = _psum_chunk_size(mesh, W)
            if chunk < 1:
                raise ValueError(
                    "pair totals exceed int32 even per single psum"
                    " slice; shrink the shard width or the per-host mesh"
                )
            hi, lo = _psum_chunked_fn(mesh, axis, "pair:" + op, chunk)(
                bits, ras, rbs
            )
            out = _hi_lo_total(hi, lo)
            _note_dispatch("pair_count", "xla", args=(bits, ras))
            return out
        t0 = time.perf_counter()
        out = _pair_count_sharded_fn(mesh, axis, op, False)(bits, ras, rbs)
        _note_dispatch(
            "pair_count", "xla", wall=time.perf_counter() - t0, args=(bits, ras)
        )
        return out
    t0 = time.perf_counter()
    out = pair_count_batched_xla(bits, ras, rbs, op=op)
    _note_dispatch(
        "pair_count", "xla", wall=time.perf_counter() - t0, args=(bits, ras)
    )
    return out


# ---------------------------------------------------------------------------
# MXU gram path: all-pairs intersection counts as int8 matmuls
# ---------------------------------------------------------------------------

# Word-block the gram scan unpacks per step: [R, wb] uint32 -> [R, wb*32]
# int8 staged for the MXU.  4096 words = 2^17 bits/row/step; per-step gram
# partials (<= 2^17 per pair) accumulate exactly in int32.
_GRAM_WB = 4096

# Past this many distinct rows the gram matrix itself gets big (U^2 int32)
# and the O(U^2) matmul work outgrows the O(B) scan — callers fall back.
GRAM_MAX_ROWS = 4096

# numpy (not jnp): a device constant created during a jit trace would be a
# tracer and must not be cached across traces
_SHIFTS32 = np.arange(32, dtype=np.uint32)


def _gram_word_block(w: int) -> int:
    return _word_block(w, _GRAM_WB)


def _gram_blocks(bits: jax.Array, wb: int) -> jax.Array:
    """[S, R, W] -> [S*nb, R, wb] word blocks in scan order."""
    S, R, W = bits.shape
    nb = W // wb
    return bits.reshape(S, R, nb, wb).transpose(0, 2, 1, 3).reshape(
        S * nb, R, wb
    )


def _unpack_int8(blk: jax.Array) -> jax.Array:
    """[R, wb] uint32 words -> [R, wb*32] int8 0/1 for the MXU."""
    R, wb = blk.shape
    return ((blk[:, :, None] >> _SHIFTS32) & 1).astype(jnp.int8).reshape(
        R, wb * 32
    )


# fused-gram Pallas blocks: shards per step, and a VMEM budget for the
# in-kernel int8 unpack (R * wb * 32 bytes must fit comfortably)
_GRAM_PALLAS_SB = 8
_GRAM_PALLAS_UNPACK_BYTES = 4 << 20


def _bit_slabs(blk):
    """[R, wb] uint32 -> [R, wb*32] int8 0/1 inside a Pallas kernel:
    32 shift/mask slabs concatenated along the lane axis.  The self- and
    cross-gram kernels MUST share this (their column permutations have
    to agree with each other and be self-consistent for the gram)."""
    return jnp.concatenate(
        [
            ((blk >> jnp.uint32(k)) & jnp.uint32(1)).astype(jnp.int8)
            for k in range(32)
        ],
        axis=1,
    )


def _gram_pallas_kernel(in_ref, out_ref):
    """One [SB, R, WB] step of the self-gram: unpack each shard's word
    block to int8 bit slabs IN VMEM and feed the MXU.  The XLA scan
    materializes the 32x int8 expansion through HBM, which bounds it at
    ~2x the fused launch time (measured 33 vs 18 ms on a 10.7e9-bit
    index on one v5e chip; the remaining floor is the VPU unpack
    itself)."""
    s = pl.program_id(0)
    w = pl.program_id(1)

    @pl.when((s == 0) & (w == 0))
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    acc = jnp.zeros(out_ref.shape, jnp.int32)
    for si in range(in_ref.shape[0]):
        x = _bit_slabs(in_ref[si])  # [R, WB*32] 0/1
        acc = acc + lax.dot_general(
            x, x, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
    out_ref[...] += acc


def _gram_pallas_sb(S: int) -> int:
    """Shards per grid step: the largest divisor of S up to
    _GRAM_PALLAS_SB — a non-dividing block would force a full
    index-sized jnp.pad copy per launch (measured: sb in 1..8 performs
    identically; the scan is unpack-bound)."""
    for sb in range(min(_GRAM_PALLAS_SB, S), 0, -1):
        if S % sb == 0:
            return sb
    return 1


@partial(jax.jit, static_argnames=("sb", "wb"))
def _gram_matrix_pallas(bits: jax.Array, *, sb: int, wb: int) -> jax.Array:
    S, R, W = bits.shape
    assert S % sb == 0, (S, sb)  # use _gram_pallas_sb; a non-dividing
    return pl.pallas_call(       # block would silently drop shards
        _gram_pallas_kernel,
        grid=(S // sb, W // wb),
        in_specs=[pl.BlockSpec((sb, R, wb), lambda s, w: (s, 0, w))],
        out_specs=pl.BlockSpec((R, R), lambda s, w: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((R, R), jnp.int32),
        interpret=_interpret(),
    )(bits)


# The fused grams get their OWN gates, default ON on TPU: unlike the
# scan kernels (where fused XLA wins), they measure ~1.7-1.8x faster
# than the XLA grams.  PILOSA_TPU_NO_PALLAS_GRAM=1 reverts to XLA.
# One gate PER KERNEL: the self- and cross-gram are distinct Mosaic
# programs, so one kernel's probe result must neither vouch for nor
# condemn the other.


class _PallasGate:
    """Tri-state probe flag for one Pallas kernel family: None =
    unproven, True = proven good, False = demoted.  Past the probe,
    demotion requires MAX_FAILS LIFETIME failures — one transient
    (device OOM under load) must not disable a proven kernel, while a
    persistently broken cached program must not be re-attempted
    forever; the counter is deliberately never reset on success, so a
    healthy sibling program sharing the gate cannot starve a broken
    one's demotion."""

    __slots__ = ("ok", "fails")
    MAX_FAILS = 3

    def __init__(self):
        self.ok: bool | None = None
        self.fails = 0  # lifetime count — NOT reset on success: a gate
        # may serve several compiled programs, and a healthy one's
        # successes must not starve a broken sibling's demotion


_self_gram_gate = _PallasGate()
_cross_gram_gate = _PallasGate()


def _gram_pallas_wb(R: int, W: int) -> int:
    """The fused gram's word block for an R-row stack, or 0 when the
    kernel should not engage.  The VMEM cap must be floored to a power
    of two BEFORE _word_block halves it into W — a non-power-of-two cap
    (any non-power-of-two R) would collapse wb to 1-2 and silently
    disable the kernel."""
    cap = _GRAM_PALLAS_UNPACK_BYTES // (32 * max(R, 1))
    if cap < 1 or R < 8:
        return 0
    wb = _word_block(W, 1 << (cap.bit_length() - 1))
    return wb if wb >= 128 else 0  # lane-width floor: tiny blocks don't tile


def _gram_pallas_eligible(R: int, W: int, gate=None) -> bool:
    gate = gate or _self_gram_gate
    return (
        gate.ok is not False
        and jax.default_backend() == "tpu"
        and os.environ.get("PILOSA_TPU_NO_PALLAS_GRAM") != "1"
        and _gram_pallas_wb(R, W) > 0
    )


def gram_matrix_traced(bits: jax.Array) -> jax.Array:
    """Trace-safe gram chooser for callers embedding the gram inside
    their OWN jit (e.g. fusing a transform into the input, or a
    shard_map's per-device block): picks the fused Pallas kernel by
    static shape/backend with no runtime fallback.  Use
    :func:`gram_matrix` outside jit."""
    _, R, W = bits.shape
    if _gram_pallas_eligible(R, W):
        return _gram_matrix_pallas(
            bits, sb=_gram_pallas_sb(bits.shape[0]), wb=_gram_pallas_wb(R, W)
        )
    return gram_matrix_xla(bits)


def _with_gram_fallback(pallas_fn, fallback_fn, gate=None, kernel="gram"):
    """The gram family's shared probe/demote contract: the first success
    proves the gate; every failure — probe-time or proven — is answered
    by ``fallback_fn``, counted visibly, and charged against
    _PallasGate.MAX_FAILS LIFETIME failures before demotion (never reset
    on success — a healthy sibling program sharing the gate must not
    starve a broken one's demotion).  Probe-time failures get the same
    tolerance as proven-kernel failures: one device-OOM blip on the
    first-ever call must not silently lose the fused path for the
    process lifetime, while a genuinely broken kernel (compile error)
    still demotes after MAX_FAILS bounded re-probes."""
    gate = gate or _self_gram_gate
    try:
        # always synchronize INSIDE the try: async dispatch would let a
        # runtime failure (e.g. device OOM) surface at the caller's
        # np.asarray instead of being re-answered by the fallback — and
        # every call site pulls the result immediately anyway
        t0 = time.perf_counter()
        out = jax.block_until_ready(pallas_fn())
        if gate.ok is None:
            gate.ok = True
        _note_dispatch(kernel, "pallas", wall=time.perf_counter() - t0)
        return out
    except Exception as exc:
        probing = gate.ok is None
        _note_pallas_fallback(exc)
        gate.fails += 1
        if gate.fails >= gate.MAX_FAILS:
            gate.ok = False
        if probing:
            # a failing PROBE degrades a default-ON fast path: log each
            # attempt so the resulting latency is diagnosable
            logger.warning(
                "pallas gram probe failed (%d/%d)%s: %r",
                gate.fails,
                gate.MAX_FAILS,
                "; kernel family disabled" if gate.ok is False else "",
                exc,
            )
        t0 = time.perf_counter()
        out = fallback_fn()
        _note_dispatch(
            kernel, "xla", wall=time.perf_counter() - t0, demoted=True
        )
        return out


def gram_matrix(bits: jax.Array) -> jax.Array:
    """Self-gram dispatcher: fused-unpack Pallas kernel on TPU, XLA scan
    otherwise or on any Pallas failure."""
    _, R, W = bits.shape
    if _multi_device(bits) or not _gram_pallas_eligible(R, W):
        t0 = time.perf_counter()
        out = gram_matrix_xla(bits)
        _note_dispatch(
            "gram_matrix", "xla", wall=time.perf_counter() - t0, args=(bits,)
        )
        return out
    return _with_gram_fallback(
        lambda: gram_matrix_traced(bits),
        lambda: gram_matrix_xla(bits),
        kernel="gram_matrix",
    )


@jax.jit
def gram_matrix_xla(bits: jax.Array) -> jax.Array:
    """``G[i, j] = sum_s popcount(bits[s, i] & bits[s, j])`` for ALL row
    pairs, as one scan of the index with an int8 matmul per word block on
    the MXU (0/1 dot product == AND+popcount).

    Kept separate from :func:`cross_gram_xla` deliberately: the self-gram
    unpacks each block ONCE (cross would unpack both operands), and this
    is the hottest serving kernel.

    int32 accumulation: per-block partials are <= wb*32 and callers
    (:func:`pair_gram`) chunk the shard axis so S * W * 32 < 2^31 —
    int64 cannot be used here because without ``jax_enable_x64`` JAX
    silently narrows it back to int32."""
    _, R, W = bits.shape
    blocks = _gram_blocks(bits, _gram_word_block(W))

    def body(acc, blk):  # blk: [R, wb] uint32
        x = _unpack_int8(blk)
        g = lax.dot_general(
            x, x, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return acc + g, None

    acc0 = jnp.zeros((R, R), jnp.int32)
    acc, _ = lax.scan(body, acc0, blocks)
    return acc


@jax.jit
def gram_gather_xla(bits: jax.Array, idx: jax.Array) -> jax.Array:
    """Gram over the row subset ``bits[:, idx]`` — the batch's distinct
    rows only, so the scan reads U/R of the index."""
    return gram_matrix_xla(bits[:, idx])


@jax.jit
def _gram_gather_fused(bits: jax.Array, idx: jax.Array) -> jax.Array:
    # gather fused into the same program as the kernel (mirrors
    # _cross_gram_gather_fused: the eager form would materialize the
    # gathered copy as a standalone dispatch)
    return gram_matrix_traced(bits[:, idx])


def gram_gather(bits: jax.Array, idx: jax.Array) -> jax.Array:
    """Subset-gram dispatcher: gather+fused Pallas gram in one program
    when eligible (the in-program gather is far cheaper than the XLA
    scan's per-block int8 expansion), else the fused XLA scan."""
    U = int(idx.shape[0])
    _, _, W = bits.shape
    if not _multi_device(bits) and _gram_pallas_eligible(U, W):
        return _with_gram_fallback(
            lambda: _gram_gather_fused(bits, idx),
            lambda: gram_gather_xla(bits, idx),
            kernel="gram_gather",
        )
    t0 = time.perf_counter()
    out = gram_gather_xla(bits, idx)
    _note_dispatch(
        "gram_gather", "xla", wall=time.perf_counter() - t0, args=(bits, idx)
    )
    return out


# Largest pair total an int32 gram accumulator may reach (tests shrink it
# to exercise the chunked path on small shapes).
_GRAM_ACC_LIMIT = 2**31 - 1


def _gram_int32_safe(s: int, w: int) -> bool:
    """A pair's total fits int32 while S * W * 32 <= the limit."""
    return s * w * 32 <= _GRAM_ACC_LIMIT


def row_counts_supported(bits) -> bool:
    """Whether ``row_counts`` can serve this stack — always, except a
    process-spanning mesh so large that even a single-shard-per-device
    psum slice would overflow int32 (callers decline to per-fragment
    counting instead of catching row_counts' ValueError)."""
    m = shards_axis_of(bits)
    if m is None or not mesh_spans_processes(m[0]):
        return True
    S, _, W = bits.shape
    return _gram_int32_safe(S, W) or _psum_chunk_size(m[0], W) >= 1


def stack_spans_processes(x) -> bool:
    """Whether ``x`` is a shards-sharded stack whose mesh includes other
    processes' devices.  The decline guard for the remaining batched
    paths whose kernels return per-shard partials (not host addressable
    there) — the compiled-AST BITMAP programs (host-side Row segments)
    and the k-level GroupBy combo engine; pair/masked/row counts, the
    grams, and the compiled-AST COUNT programs now reduce in-program
    (psum) on spanning meshes instead of declining."""
    m = shards_axis_of(x)
    return m is not None and mesh_spans_processes(m[0])


@lru_cache(maxsize=64)
def mesh_spans_processes(mesh) -> bool:
    """Whether the mesh includes devices owned by other processes — the
    multi-host serving layout, where per-device partials are NOT host
    addressable and the reduce must happen in-program.  Cached: the
    answer is constant per mesh and this sits on ~0.1 ms serving
    paths."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


@lru_cache(maxsize=64)
def _gram_mesh_fn(mesh, axis, gather, in_program_reduce, use_pallas=False):
    """jit(shard_map) gram over a shards-sharded stack.  Two reduce
    modes: per-device partials stacked along the mesh axis for a
    host-side int64 sum (single-host serving), or an IN-PROGRAM
    ``lax.psum`` whose reduce rides the runtime's collectives (ICI
    within a host, DCN across — SURVEY §2.4's mapping of the
    reference's mapReduce reduce step, executor.go:2454) and whose
    result is replicated on every process — required when the mesh
    spans processes, where stacked partials would not be host
    addressable.  ``use_pallas`` routes each device's block through the
    fused-unpack gram (gram_matrix_traced picks it by static shape);
    the psum path stays XLA-only — Pallas composed with a cross-process
    collective is untestable on this single-chip dev setup."""
    if gather:
        if use_pallas:
            base = lambda b, i: gram_matrix_traced(b[:, i])
        else:
            base = lambda b, i: gram_gather_xla(b, i)
        in_specs = (P(axis, None, None), P(None))
    else:
        if use_pallas:
            base = lambda b: gram_matrix_traced(b)
        else:
            base = lambda b: gram_matrix_xla(b)
        in_specs = (P(axis, None, None),)
    if in_program_reduce:
        local = lambda *a: lax.psum(base(*a), axis)
        out_specs = P(None, None)
    else:
        local = lambda *a: base(*a)[None]
        out_specs = P(axis, None, None)
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            # the gram scan's zero-init carry is replicated while the
            # shard blocks vary per device; the accumulation is still
            # purely local so the vma check is safe to relax
            check_vma=False,
        )
    )


def _carry_psum_chunks(local_partial, arrs, axis, chunk):
    """In-program exact accumulation past int32: loop the device-local
    shard block in ``chunk``-shard slices, psum each slice's int32
    partial across the mesh axis, and accumulate into a (hi, lo) uint32
    carry-save pair (device int64 is unavailable without x64).  The
    caller picks ``chunk`` so one slice's GLOBAL psum total is
    int32-exact."""
    s_loc = arrs[0].shape[0]
    n_chunks = -(-s_loc // chunk)
    pad = n_chunks * chunk - s_loc
    arrs = tuple(
        jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1)) for a in arrs
    )
    shape = jax.eval_shape(
        local_partial,
        *(
            jax.ShapeDtypeStruct((chunk,) + a.shape[1:], a.dtype)
            for a in arrs
        ),
    ).shape

    def body(i, acc):
        hi, lo = acc
        blks = tuple(
            lax.dynamic_slice_in_dim(a, i * chunk, chunk, 0) for a in arrs
        )
        p = lax.psum(local_partial(*blks), axis).astype(jnp.uint32)
        new_lo = lo + p
        # p < 2^32, so the add wrapped iff the result went down
        hi = hi + (new_lo < lo).astype(jnp.uint32)
        return hi, new_lo

    z = jnp.zeros(shape, jnp.uint32)
    return lax.fori_loop(0, n_chunks, body, (z, z))


@lru_cache(maxsize=64)
def _psum_chunked_fn(mesh, axis, kind, chunk):
    """jit(shard_map) for process-spanning meshes whose totals exceed
    int32: returns replicated (hi, lo) uint32 arrays to combine on host
    as hi * 2^32 + lo."""
    if kind == "gram":
        local = lambda b: _carry_psum_chunks(
            gram_matrix_xla, (b,), axis, chunk
        )
        in_specs = (P(axis, None, None),)
        out = P(None, None)
    elif kind == "gram_gather":
        local = lambda b, i: _carry_psum_chunks(
            lambda blk: gram_gather_xla(blk, i), (b,), axis, chunk
        )
        in_specs = (P(axis, None, None), P(None))
        out = P(None, None)
    elif kind == "cross":
        local = lambda a, b, ia, ib: _carry_psum_chunks(
            lambda x, y: cross_gram_xla(x[:, ia], y[:, ib]),
            (a, b),
            axis,
            chunk,
        )
        in_specs = (
            P(axis, None, None), P(axis, None, None), P(None), P(None)
        )
        out = P(None, None)
    elif kind.startswith("pair2:"):
        op = kind.split(":", 1)[1]
        local = lambda a, b, ra, rb: _carry_psum_chunks(
            lambda x, y: jnp.sum(
                pair_count_two_batched_xla(x, y, ra, rb, op=op), axis=1
            ),
            (a, b),
            axis,
            chunk,
        )
        in_specs = (
            P(axis, None, None), P(axis, None, None), P(None), P(None)
        )
        out = P(None)
    elif kind.startswith("pair:"):
        op = kind.split(":", 1)[1]
        local = lambda b, ra, rb: _carry_psum_chunks(
            lambda x: jnp.sum(
                pair_count_batched_xla(x, ra, rb, op=op), axis=1
            ),
            (b,),
            axis,
            chunk,
        )
        in_specs = (P(axis, None, None), P(None), P(None))
        out = P(None)
    elif kind == "masked_rows":
        local = lambda b, f: _carry_psum_chunks(
            lambda x, ff: jnp.sum(masked_row_counts_xla(x, ff), axis=0),
            (b, f),
            axis,
            chunk,
        )
        in_specs = (P(axis, None, None), P(axis, None))
        out = P(None)
    else:  # rows
        local = lambda b: _carry_psum_chunks(
            row_counts_xla, (b,), axis, chunk
        )
        in_specs = (P(axis, None, None),)
        out = P(None)
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(out, out),
            check_vma=False,
        )
    )


def _psum_chunk_size(mesh, w: int) -> int:
    """Per-device shards per chunked psum so one slice's global total
    stays int32-exact; 0 when even a single shard per device overflows
    (callers decline)."""
    return _GRAM_ACC_LIMIT // max(1, mesh.devices.size * w * 32)


def _hi_lo_total(hi, lo) -> np.ndarray:
    return _pull(hi).astype(np.int64) * 2**32 + _pull(lo).astype(np.int64)


def pair_gram(bits: jax.Array, row_idx) -> np.ndarray | None:
    """``int64 numpy [U, U]`` intersection counts between every pair of
    the rows named by ``row_idx``, summed over all shards — the
    one-launch answer to a whole batch of pair-count queries
    (reference executor.go:653-680 + roaring.go:568, re-shaped for the
    MXU).  None when ``row_idx`` is too wide for the gram path
    (> GRAM_MAX_ROWS); callers fall back to the scan kernels, which
    serve process-spanning meshes too via in-program psum (replicated
    int64 totals instead of per-shard partials — kernels.py r05).

    Works on single-device and shards-axis NamedSharding'd stacks; on a
    single-host mesh each device grams its local shard block and the
    host reduces, while a process-spanning mesh reduces in-program
    (psum, carry-save chunked past int32).
    """
    S, R, W = bits.shape
    U = len(row_idx)
    if U == 0 or U > GRAM_MAX_ROWS:
        return None
    full = U == R and list(row_idx) == list(range(R))
    if not full:
        # pad the gather to a power of two (repeating row 0) so jit
        # programs are reused as the batch's distinct-row count drifts
        Up = pow2_pad_len(U)
        idx = np.zeros(Up, np.int32)
        idx[:U] = row_idx
        if Up > U:
            # padded vs useful gather-subset bytes ([S, Up, W] uint32)
            note_pad("pair_gram", S * Up * W * 4, S * U * W * 4)
    m = shards_axis_of(bits)
    if m is not None:
        mesh, axis = m
        if mesh_spans_processes(mesh):
            # multi-host stack: reduce in-program (psum over DCN/ICI) —
            # per-device partials aren't host addressable here
            if _gram_int32_safe(S, W):
                fn = _gram_mesh_fn(mesh, axis, not full, True)
                out = fn(bits) if full else fn(bits, jnp.asarray(idx))
                return _pull(out).astype(np.int64)[:U, :U]
            chunk = _psum_chunk_size(mesh, W)
            if chunk < 1:
                return None
            fn = _psum_chunked_fn(
                mesh, axis, "gram_gather" if not full else "gram", chunk
            )
            hi, lo = fn(bits) if full else fn(bits, jnp.asarray(idx))
            return _hi_lo_total(hi, lo)[:U, :U]
        if not _gram_int32_safe(-(-S // mesh.devices.size), W):
            # a device-local partial could wrap int32; callers fall back
            # to the scan kernels' [B, S] per-shard partials
            return None
        # eligibility must consider the shape the per-device base will
        # actually see (the padded gather subset, not the stack's R) —
        # a True-variant program that would trace to pure XLA anyway
        # must not own the Pallas gate's failure semantics
        use_p = _gram_pallas_eligible(R if full else len(idx), W)

        def _run(with_pallas: bool):
            fn = _gram_mesh_fn(mesh, axis, not full, False, with_pallas)
            return fn(bits) if full else fn(bits, jnp.asarray(idx))

        if use_p:
            out = _with_gram_fallback(
                lambda: _run(True), lambda: _run(False), kernel="pair_gram"
            )
        else:
            t0 = time.perf_counter()
            out = _run(False)
            _note_dispatch(
                "pair_gram", "xla", wall=time.perf_counter() - t0, args=(bits,)
            )
        return _pull(out).astype(np.int64).sum(axis=0)[:U, :U]
    if _gram_int32_safe(S, W):
        if full:
            out = gram_matrix(bits)
        else:
            out = gram_gather(bits, jnp.asarray(idx))
        return _pull(out).astype(np.int64)[:U, :U]
    # Giant single-device index: chunk the shard axis so each chunk's
    # partial gram is int32-exact, and sum the chunks in host int64
    # (int64 on device is unavailable without jax_enable_x64).
    chunk = max(1, _GRAM_ACC_LIMIT // (W * 32))
    total = np.zeros((U, U) if full else (len(idx), len(idx)), np.int64)
    for c0 in range(0, S, chunk):
        blk = bits[c0 : c0 + chunk]
        out = gram_matrix(blk) if full else gram_gather(
            blk, jnp.asarray(idx)
        )
        total += _pull(out).astype(np.int64)
    return total[:U, :U]


def pair_counts_from_gram(
    gram: np.ndarray, pa: np.ndarray, pb: np.ndarray, op: str
) -> np.ndarray:
    """Evaluate a batch of pair-op counts from gram entries.  ``pa/pb``
    index into the gram's row-subset coordinates."""
    g = gram[pa, pb]
    if op == "intersect":
        return g
    da = gram[pa, pa]
    if op == "difference":
        return da - g
    db = gram[pb, pb]
    if op == "union":
        return da + db - g
    if op == "xor":
        return da + db - 2 * g
    raise ValueError(f"unknown pair op: {op}")


@jax.jit
def cross_gram_xla(bits_a: jax.Array, bits_b: jax.Array) -> jax.Array:
    """``G[i, j] = sum_s popcount(bits_a[s, i] & bits_b[s, j])`` for ALL
    cross-field row pairs — the 2-level GroupBy combination matrix
    (reference executor.go:3208-3211 counts the intersection of the last
    two levels per combination; one MXU scan answers every combination).
    int32 accumulation; callers chunk shards via :func:`cross_pair_gram`.
    """
    S, Ra, W = bits_a.shape
    Rb = bits_b.shape[1]
    wb = _gram_word_block(W)
    blocks_a = _gram_blocks(bits_a, wb)
    blocks_b = _gram_blocks(bits_b, wb)

    def body(acc, blk):
        ba, bb = blk
        xa = _unpack_int8(ba)
        xb = _unpack_int8(bb)
        g = lax.dot_general(
            xa, xb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return acc + g, None

    acc0 = jnp.zeros((Ra, Rb), jnp.int32)
    acc, _ = lax.scan(body, acc0, (blocks_a, blocks_b))
    return acc


@jax.jit
def cross_gram_gather_xla(
    bits_a: jax.Array, bits_b: jax.Array, ia: jax.Array, ib: jax.Array
) -> jax.Array:
    """Cross gram over row subsets, gathered inside the program."""
    return cross_gram_xla(bits_a[:, ia], bits_b[:, ib])


def _cross_gram_pallas_kernel(a_ref, b_ref, out_ref):
    """Fused-unpack cross gram — both operands' word blocks unpack to
    int8 bit slabs in VMEM (same bottleneck analysis as
    _gram_pallas_kernel; the cross variant pays the VPU unpack twice)."""
    s = pl.program_id(0)
    w = pl.program_id(1)

    @pl.when((s == 0) & (w == 0))
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    acc = jnp.zeros(out_ref.shape, jnp.int32)
    for si in range(a_ref.shape[0]):
        acc = acc + lax.dot_general(
            _bit_slabs(a_ref[si]),
            _bit_slabs(b_ref[si]),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
    out_ref[...] += acc


@partial(jax.jit, static_argnames=("sb", "wb"))
def _cross_gram_pallas(
    bits_a: jax.Array, bits_b: jax.Array, *, sb: int, wb: int
) -> jax.Array:
    S, Ra, W = bits_a.shape
    Rb = bits_b.shape[1]
    assert S % sb == 0, (S, sb)  # see _gram_matrix_pallas
    return pl.pallas_call(
        _cross_gram_pallas_kernel,
        grid=(S // sb, W // wb),
        in_specs=[
            pl.BlockSpec((sb, Ra, wb), lambda s, w: (s, 0, w)),
            pl.BlockSpec((sb, Rb, wb), lambda s, w: (s, 0, w)),
        ],
        out_specs=pl.BlockSpec((Ra, Rb), lambda s, w: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((Ra, Rb), jnp.int32),
        interpret=_interpret(),
    )(bits_a, bits_b)


def _cross_pallas_engages(Ra: int, Rb: int, W: int) -> bool:
    """The ONE cross-gram Pallas predicate — cross_gram_traced and every
    call site that wraps it in _with_gram_fallback must share it, or a
    desynced gate would let a quietly-XLA trace falsely prove the
    Pallas gate.  Both operands' unpacked slabs share the VMEM budget,
    so eligibility uses Ra + Rb."""
    return (
        Ra >= 8
        and Rb >= 8
        and _gram_pallas_eligible(Ra + Rb, W, gate=_cross_gram_gate)
    )


def cross_gram_traced(bits_a: jax.Array, bits_b: jax.Array) -> jax.Array:
    """Trace-safe cross-gram chooser (see gram_matrix_traced)."""
    _, Ra, W = bits_a.shape
    Rb = bits_b.shape[1]
    if _cross_pallas_engages(Ra, Rb, W):
        return _cross_gram_pallas(
            bits_a,
            bits_b,
            sb=_gram_pallas_sb(bits_a.shape[0]),
            wb=_gram_pallas_wb(Ra + Rb, W),
        )
    return cross_gram_xla(bits_a, bits_b)


@jax.jit
def _cross_gram_gather_fused(
    bits_a: jax.Array, bits_b: jax.Array, ia: jax.Array, ib: jax.Array
) -> jax.Array:
    # gather fused into the same program as the kernel (the eager form
    # would materialize the gathered copies as standalone dispatches)
    return cross_gram_traced(bits_a[:, ia], bits_b[:, ib])


def cross_gram_gather(
    bits_a: jax.Array, bits_b: jax.Array, ia: jax.Array, ib: jax.Array
) -> jax.Array:
    """Subset cross-gram dispatcher with the gram family's runtime
    fallback semantics."""
    _, _, W = bits_a.shape
    Ua, Ub = int(ia.shape[0]), int(ib.shape[0])
    if (
        _multi_device(bits_a)
        or _multi_device(bits_b)
        or not _cross_pallas_engages(Ua, Ub, W)
    ):
        t0 = time.perf_counter()
        out = cross_gram_gather_xla(bits_a, bits_b, ia, ib)
        _note_dispatch(
            "cross_gram_gather",
            "xla",
            wall=time.perf_counter() - t0,
            args=(bits_a, ia, ib),
        )
        return out
    return _with_gram_fallback(
        lambda: _cross_gram_gather_fused(bits_a, bits_b, ia, ib),
        lambda: cross_gram_gather_xla(bits_a, bits_b, ia, ib),
        gate=_cross_gram_gate,
        kernel="cross_gram_gather",
    )


@lru_cache(maxsize=64)
def _cross_gram_mesh_fn(mesh, axis, in_program_reduce):
    """Cross gram over aligned shards-sharded stacks — stacked partials
    for a host-side sum, or an in-program psum reduce for
    process-spanning meshes (same two modes as _gram_mesh_fn)."""
    base = lambda a, b, ia, ib: cross_gram_xla(a[:, ia], b[:, ib])
    if in_program_reduce:
        local = lambda *args: lax.psum(base(*args), axis)
        out_specs = P(None, None)
    else:
        local = lambda *args: base(*args)[None]
        out_specs = P(axis, None, None)
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(
                P(axis, None, None), P(axis, None, None), P(None), P(None)
            ),
            out_specs=out_specs,
            check_vma=False,  # same local-accumulation argument as
        )  # _gram_mesh_fn
    )


def _cross_gram_sharded_fn(mesh, axis):
    return _cross_gram_mesh_fn(mesh, axis, False)


def _cross_gram_psum_fn(mesh, axis):
    return _cross_gram_mesh_fn(mesh, axis, True)


def cross_pair_gram(bits_a: jax.Array, bits_b: jax.Array, idx_a, idx_b):
    """``int64 numpy [Ua, Ub]`` cross-field intersection counts between
    the named row subsets, summed over all shards; None when a subset is
    too wide (callers fall back to the batched scan kernels).  Both
    stacks must share the (aligned, equally-sharded) shard axis."""
    S, _, W = bits_a.shape
    Ua, Ub = len(idx_a), len(idx_b)
    if Ua == 0 or Ub == 0 or max(Ua, Ub) > GRAM_MAX_ROWS:
        return None
    # pad gathers to powers of two for program reuse
    ia = np.zeros(pow2_pad_len(Ua), np.int32)
    ia[:Ua] = idx_a
    ib = np.zeros(pow2_pad_len(Ub), np.int32)
    ib[:Ub] = idx_b
    if len(ia) > Ua or len(ib) > Ub:
        note_pad(
            "cross_pair_gram",
            S * (len(ia) + len(ib)) * W * 4,
            S * (Ua + Ub) * W * 4,
        )
    m = shards_axis_of(bits_a)
    if m is not None and shards_axis_of(bits_b) == m:
        mesh, axis = m
        if mesh_spans_processes(mesh):
            # in-program psum reduce (see pair_gram's spanning branch)
            if _gram_int32_safe(S, W):
                out = _cross_gram_psum_fn(mesh, axis)(
                    bits_a, bits_b, jnp.asarray(ia), jnp.asarray(ib)
                )
                return _pull(out).astype(np.int64)[:Ua, :Ub]
            chunk = _psum_chunk_size(mesh, W)
            if chunk < 1:
                return None
            hi, lo = _psum_chunked_fn(mesh, axis, "cross", chunk)(
                bits_a, bits_b, jnp.asarray(ia), jnp.asarray(ib)
            )
            return _hi_lo_total(hi, lo)[:Ua, :Ub]
        if not _gram_int32_safe(-(-S // mesh.devices.size), W):
            return None
        out = _cross_gram_sharded_fn(mesh, axis)(
            bits_a, bits_b, jnp.asarray(ia), jnp.asarray(ib)
        )
        return _pull(out).astype(np.int64).sum(axis=0)[:Ua, :Ub]
    if m is not None or shards_axis_of(bits_b) is not None:
        return None  # mismatched shardings; scan kernels handle it
    ia_d, ib_d = jnp.asarray(ia), jnp.asarray(ib)
    if _gram_int32_safe(S, W):
        out = cross_gram_gather(bits_a, bits_b, ia_d, ib_d)
        return _pull(out).astype(np.int64)[:Ua, :Ub]
    chunk = max(1, _GRAM_ACC_LIMIT // (W * 32))
    total = np.zeros((len(ia), len(ib)), np.int64)
    for c0 in range(0, S, chunk):
        out = cross_gram_gather(
            bits_a[c0 : c0 + chunk], bits_b[c0 : c0 + chunk], ia_d, ib_d
        )
        total += _pull(out).astype(np.int64)
    return total[:Ua, :Ub]


# ---------------------------------------------------------------------------
# Two-tensor pair count: Count(op(A.Row(ra[i]), B.Row(rb[i])))  (GroupBy)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("op",))
def pair_count_two_batched_xla(
    bits_a: jax.Array, bits_b: jax.Array, ras: jax.Array, rbs: jax.Array,
    *, op: str = "intersect",
) -> jax.Array:
    def body(_, q):
        ra, rb = q
        words = _OPS[op](bits_a[:, ra], bits_b[:, rb])
        return None, jnp.sum(
            lax.population_count(words).astype(jnp.int32), axis=-1
        )

    _, counts = lax.scan(body, None, (ras, rbs))
    return counts


def pair_count_two_batched(
    bits_a: jax.Array, bits_b: jax.Array, ras: jax.Array, rbs: jax.Array,
    *, op: str = "intersect",
):
    """Cross-tensor pair counts; same return contract as
    ``pair_count_batched``: device ``int32[B, S]`` partials on local
    stacks, replicated ``np.int64[B]`` in-program psum totals on a
    process-spanning mesh."""
    m = shards_axis_of(bits_a)
    if m is not None and shards_axis_of(bits_b) == m:
        mesh, axis = m
        if mesh_spans_processes(mesh):
            _, _, W = bits_a.shape
            chunk = _psum_chunk_size(mesh, W)
            if chunk < 1:
                raise ValueError(
                    "pair totals exceed int32 even per single psum"
                    " slice; shrink the shard width or the per-host mesh"
                )
            hi, lo = _psum_chunked_fn(mesh, axis, "pair2:" + op, chunk)(
                bits_a, bits_b, ras, rbs
            )
            out = _hi_lo_total(hi, lo)
            _note_dispatch("pair_count_two", "xla", args=(bits_a, ras))
            return out
        t0 = time.perf_counter()
        out = _pair_count_sharded_fn(mesh, axis, op, True)(
            bits_a, bits_b, ras, rbs
        )
        _note_dispatch(
            "pair_count_two",
            "xla",
            wall=time.perf_counter() - t0,
            args=(bits_a, ras),
        )
        return out
    t0 = time.perf_counter()
    out = pair_count_two_batched_xla(bits_a, bits_b, ras, rbs, op=op)
    _note_dispatch(
        "pair_count_two",
        "xla",
        wall=time.perf_counter() - t0,
        args=(bits_a, ras),
    )
    return out


# ---------------------------------------------------------------------------
# Row-scan popcount: counts[r] = sum_s sum_w popcount(bits[s, r, w])
# ---------------------------------------------------------------------------


def _row_scan_kernel(in_ref, out_ref):
    """Accumulate per-(shard, row) popcounts over the word-block grid
    axis.  Blocks are (SB shards, ALL rows, wb words) — dimensions that
    satisfy the TPU (8, 128) tiling rule (the row axis equals the full
    array dimension; earlier (1, rows, W) one-shard blocks did not
    compile)."""
    w = pl.program_id(1)
    pc = jnp.sum(
        lax.population_count(in_ref[...]).astype(jnp.int32), axis=-1
    )  # [SB, R]

    @pl.when(w == 0)
    def _():
        out_ref[...] = pc

    @pl.when(w != 0)
    def _():
        out_ref[...] = out_ref[...] + pc


# shards per Pallas grid block (sublane-aligned)
_SHARD_BLOCK = 8
# word-block cap for the Pallas row scans
_PALLAS_WB = 2048
# per-tile byte target: an (sb, R, wb) uint32 block plus double buffering
# must stay inside VMEM (~16 MiB on v5e)
_PALLAS_VMEM_BUDGET = 8 << 20


def _pallas_row_block(w: int, r: int) -> int:
    """Word-block for an (SHARD_BLOCK, r, wb) tile within the VMEM
    budget; 0 when no dividing block fits (callers use the XLA scan —
    trying Pallas anyway would fail compile and permanently demote the
    backend via _pallas_ok)."""
    wb = _word_block(w, _PALLAS_WB)
    while wb > 1 and _SHARD_BLOCK * r * wb * 4 > _PALLAS_VMEM_BUDGET:
        if w % (wb // 2):
            break
        wb //= 2
    if _SHARD_BLOCK * r * wb * 4 > _PALLAS_VMEM_BUDGET or wb < 128:
        return 0
    return wb


@jax.jit
def row_counts_per_shard_pallas(bits: jax.Array) -> jax.Array:
    """``int32[S, R]`` per-shard row popcounts (int32-safe per shard);
    callers sum across shards in int64 host-side.  Measured ~106 GB/s on
    v5e vs ~154 GB/s for the fused-XLA scan — kept for hardware where
    the balance differs (PILOSA_TPU_PALLAS=1)."""
    S, R, W = bits.shape
    sb = _SHARD_BLOCK
    wb = _pallas_row_block(W, R)
    if not wb:
        return row_counts_per_shard_xla(bits)  # tile cannot fit VMEM
    pad = (-S) % sb
    if pad:
        bits = jnp.pad(bits, ((0, pad), (0, 0), (0, 0)))
    Sp = S + pad
    out = pl.pallas_call(
        _row_scan_kernel,
        grid=(Sp // sb, W // wb),
        in_specs=[
            pl.BlockSpec((sb, R, wb), lambda s, w: (s, 0, w)),
        ],
        out_specs=pl.BlockSpec((sb, R), lambda s, w: (s, 0)),
        out_shape=jax.ShapeDtypeStruct((Sp, R), jnp.int32),
        interpret=_interpret(),
    )(bits)
    return out[:S]


@jax.jit
def row_counts_pallas(bits: jax.Array) -> jax.Array:
    """``int32[R]`` popcount per row over all shards (TopN scan,
    reference fragment.go:459-498); the cross-shard sum fuses onto the
    per-shard Pallas scan under jit."""
    return jnp.sum(row_counts_per_shard_pallas(bits), axis=0)


@jax.jit
def row_counts_xla(bits: jax.Array) -> jax.Array:
    return jnp.sum(lax.population_count(bits).astype(jnp.int32), axis=(0, 2))


@jax.jit
def row_counts_per_shard_xla(bits: jax.Array) -> jax.Array:
    return jnp.sum(lax.population_count(bits).astype(jnp.int32), axis=2)


# ---------------------------------------------------------------------------
# GroupBy combo kernels: iterated batched intersect-counts over a running
# set of prefix masks (reference executor.go:3057-3230 runs one
# intersectionCount per combination; here one launch per LEVEL).
# ---------------------------------------------------------------------------


@jax.jit
def combo_counts(prefix: jax.Array, bits: jax.Array, idx: jax.Array) -> jax.Array:
    """``int32[C, Rl, S]`` per-shard counts of every (prefix combo, row)
    intersection: popcount(prefix[c] & bits[:, idx[r]]).  A scan over the
    level's rows keeps peak memory at one [C, S, W] intermediate."""

    def body(_, r):
        rowsl = bits[:, r]  # [S, W]
        return None, jnp.sum(
            lax.population_count(prefix & rowsl[None]).astype(jnp.int32),
            axis=-1,
        )  # [C, S]

    _, out = lax.scan(body, None, idx)  # [Rl, C, S]
    return jnp.transpose(out, (1, 0, 2))


@jax.jit
def _combo_gram_xla(prefix: jax.Array, bits: jax.Array, idx: jax.Array):
    return cross_gram_xla(jnp.transpose(prefix, (1, 0, 2)), bits[:, idx])


@jax.jit
def _combo_gram_fused(prefix: jax.Array, bits: jax.Array, idx: jax.Array):
    # trace-time chooser: Pallas when the gate/shape allow (the caller
    # guards with _gram_pallas_eligible and _with_gram_fallback)
    return cross_gram_traced(jnp.transpose(prefix, (1, 0, 2)), bits[:, idx])


def combo_counts_gram(prefix: jax.Array, bits: jax.Array, idx) -> np.ndarray | None:
    """``int64 numpy [C, Rl]`` totals of every (prefix combo, row)
    intersection as ONE cross gram on the MXU — the k-level GroupBy's
    per-level count (reference executor.go:3208-3211), reading the
    prefix masks once instead of once per row.  None when a total could
    wrap int32 (S * W * 32 past the limit) or the level is too small for
    the unpack to pay off; callers fall back to :func:`combo_counts`."""
    C = prefix.shape[0]
    S, _, W = bits.shape
    if not _gram_int32_safe(S, W) or C * len(idx) < 32:
        return None
    if max(C, len(idx)) > GRAM_MAX_ROWS:
        # same cap as every gram wrapper: the per-step int8 unpack is
        # [C, wb*32] — a 65k-combo prefix would stage gigabytes where the
        # scan fallback peaks at one [C, S, W] intermediate
        return None
    if shards_axis_of(bits) is not None or _multi_device(prefix):
        # the gram scans over the SHARD axis, which would force GSPMD to
        # replicate prefix + stack onto every device; the scan kernels
        # iterate rows and partition cleanly, so decline
        return None
    idx_dev = jnp.asarray(idx, jnp.int32)
    # the shared predicate keeps this gate in lockstep with
    # cross_gram_traced (a desync would falsely prove the Pallas gate
    # from a quietly-XLA trace); a replicated multi-device stack (no
    # shards axis, >1 device) must keep the XLA path, which partitions
    # cleanly
    if not _multi_device(bits) and _cross_pallas_engages(C, len(idx), W):
        out = _with_gram_fallback(
            lambda: _combo_gram_fused(prefix, bits, idx_dev),
            lambda: _combo_gram_xla(prefix, bits, idx_dev),
            gate=_cross_gram_gate,
            kernel="combo_gram",
        )
    else:
        t0 = time.perf_counter()
        out = _combo_gram_xla(prefix, bits, idx_dev)
        _note_dispatch(
            "combo_gram",
            "xla",
            wall=time.perf_counter() - t0,
            args=(prefix, bits, idx_dev),
        )
    return _pull(out).astype(np.int64)


@jax.jit
def refine_prefix(
    prefix: jax.Array, bits: jax.Array, cis: jax.Array, ris: jax.Array
) -> jax.Array:
    """Next level's surviving prefix masks:
    ``prefix[cis[i]] & bits[:, ris[i]]`` -> [C', S, W]."""
    return prefix[cis] & jnp.transpose(bits[:, ris], (1, 0, 2))


@jax.jit
def gather_prefix(bits: jax.Array, idx: jax.Array) -> jax.Array:
    """Level-0 prefix masks: rows of a stack as [C, S, W]."""
    return jnp.transpose(bits[:, idx], (1, 0, 2))


# ---------------------------------------------------------------------------
# Masked row-scan: counts[s, r] = sum_w popcount(bits[s, r, w] & filt[s, w])
# (filtered TopN: every row intersected with a source bitmap in one launch)
# ---------------------------------------------------------------------------


def _masked_row_scan_kernel(bits_ref, filt_ref, out_ref):
    w = pl.program_id(1)
    words = bits_ref[...] & filt_ref[...][:, None, :]
    pc = jnp.sum(lax.population_count(words).astype(jnp.int32), axis=-1)

    @pl.when(w == 0)
    def _():
        out_ref[...] = pc

    @pl.when(w != 0)
    def _():
        out_ref[...] = out_ref[...] + pc


@jax.jit
def masked_row_counts_pallas(bits: jax.Array, filt: jax.Array) -> jax.Array:
    """``int32[S, R]`` per-shard popcounts of every row ANDed with a
    per-shard filter bitmap — the one-launch replacement for the
    per-shard host loop in filtered TopN (reference fragment.go:1586-1655
    topWithFilter).  Same (8-shard, full-row, word-block) tiling as
    :func:`row_counts_per_shard_pallas`."""
    S, R, W = bits.shape
    sb = _SHARD_BLOCK
    wb = _pallas_row_block(W, R)
    if not wb:
        return masked_row_counts_xla(bits, filt)  # tile cannot fit VMEM
    pad = (-S) % sb
    if pad:
        bits = jnp.pad(bits, ((0, pad), (0, 0), (0, 0)))
        filt = jnp.pad(filt, ((0, pad), (0, 0)))
    Sp = S + pad
    out = pl.pallas_call(
        _masked_row_scan_kernel,
        grid=(Sp // sb, W // wb),
        in_specs=[
            pl.BlockSpec((sb, R, wb), lambda s, w: (s, 0, w)),
            pl.BlockSpec((sb, wb), lambda s, w: (s, w)),
        ],
        out_specs=pl.BlockSpec((sb, R), lambda s, w: (s, 0)),
        out_shape=jax.ShapeDtypeStruct((Sp, R), jnp.int32),
        interpret=_interpret(),
    )(bits, filt)
    return out[:S]


@jax.jit
def masked_row_counts_xla(bits: jax.Array, filt: jax.Array) -> jax.Array:
    return jnp.sum(
        lax.population_count(bits & filt[:, None, :]).astype(jnp.int32), axis=2
    )


def _row_counts_psum_fn(mesh, axis):
    return _row_counts_mesh_fn(mesh, axis, False, True)


@lru_cache(maxsize=64)
def _masked_row_counts_sharded_fn(mesh, axis, use_pallas):
    local = masked_row_counts_pallas if use_pallas else masked_row_counts_xla
    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None, None), P(axis, None)),
            out_specs=P(axis, None),
        )
    )


def masked_row_counts(bits: jax.Array, filt: jax.Array):
    """``int64[R]`` numpy: per-row popcount of (row & filter) summed over
    shards.  One launch for every (shard, row) — kills the per-shard
    dispatch loop of filtered TopN."""
    m = shards_axis_of(bits)
    if m is not None:
        mesh, axis = m
        if mesh_spans_processes(mesh):
            # in-program psum (chunked hi/lo carry-save past int32):
            # filtered TopN stays on the fast lane across hosts
            _, _, W = bits.shape
            chunk = _psum_chunk_size(mesh, W)
            if chunk < 1:
                raise ValueError(
                    "masked row totals exceed int32 even per single"
                    " psum slice; shrink the shard width or the"
                    " per-host mesh"
                )
            fspec = NamedSharding(mesh, P(axis, None))
            if getattr(filt, "sharding", None) != fspec:
                filt = jax.device_put(np.asarray(filt), fspec)
            hi, lo = _psum_chunked_fn(mesh, axis, "masked_rows", chunk)(
                bits, filt
            )
            return _hi_lo_total(hi, lo)
        fspec = NamedSharding(mesh, P(axis, None))
        if getattr(filt, "sharding", None) != fspec:
            filt = jax.device_put(np.asarray(filt), fspec)
        partials = _run_sharded(
            _masked_row_counts_sharded_fn, (mesh, axis), (bits, filt)
        )
    else:
        partials = _try_pallas(
            masked_row_counts_pallas, masked_row_counts_xla, bits, filt
        )
    return np.asarray(partials).astype(np.int64).sum(axis=0)


def _int32_safe(bits) -> bool:
    """Cross-shard per-row totals fit int32 when S * shard_bits < 2^31."""
    S, _, W = bits.shape
    return S * W * 32 < 2**31


def row_counts(bits: jax.Array):
    """Per-row popcounts over all shards.

    Returns an ``int32[R]`` device array on the fused path, or an
    ``int64[R]`` numpy array when cross-shard totals could overflow
    int32 or the stack is mesh-sharded (per-shard device partials summed
    host-side)."""
    m = shards_axis_of(bits)
    if m is not None:
        mesh, axis = m
        if mesh_spans_processes(mesh):
            S, _, W = bits.shape
            if _gram_int32_safe(S, W):
                out = _row_counts_psum_fn(mesh, axis)(bits)
                return np.asarray(out).astype(np.int64)
            chunk = _psum_chunk_size(mesh, W)
            if chunk < 1:
                raise ValueError(
                    "row totals exceed int32 even per single psum slice;"
                    " shrink the shard width or the per-host mesh"
                )
            hi, lo = _psum_chunked_fn(mesh, axis, "rows", chunk)(bits)
            return _hi_lo_total(hi, lo)
        partials = _run_sharded(_row_counts_sharded_fn, m, (bits,))
        return np.asarray(partials).astype(np.int64).sum(axis=0)
    if _int32_safe(bits):
        return _try_pallas(row_counts_pallas, row_counts_xla, bits)
    partials = _try_pallas(
        row_counts_per_shard_pallas, row_counts_per_shard_xla, bits
    )
    return np.asarray(partials).astype(np.int64).sum(axis=0)


@partial(jax.jit, static_argnames=("n",))
def _topn_pallas(bits: jax.Array, *, n: int):
    return lax.top_k(row_counts_pallas(bits), n)


@partial(jax.jit, static_argnames=("n",))
def _topn_xla(bits: jax.Array, *, n: int):
    return lax.top_k(row_counts_xla(bits), n)


def topn_counts(bits: jax.Array, n: int):
    """(top-n counts, row slots) fused with the row scan in one launch
    (reference fragment.go:1568-1700 TopN over the ranked cache). Falls
    back to host-side int64 selection when totals could overflow int32
    or the stack is mesh-sharded."""
    if shards_axis_of(bits) is None and _int32_safe(bits):
        return _try_pallas(
            partial(_topn_pallas, n=n), partial(_topn_xla, n=n), bits
        )
    counts = row_counts(bits)  # int64 numpy on this path
    n = min(n, counts.shape[0])
    slots = np.argsort(-counts, kind="stable")[:n]
    return counts[slots], slots
