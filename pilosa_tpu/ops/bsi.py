"""Bit-sliced-index (BSI) kernels.

The reference stores integer fields bit-sliced: row 0 = exists bit, row 1 =
sign bit, rows 2..2+bitDepth = magnitude bit-planes (reference
fragment.go:90-96 ``bsiExistsBit/bsiSignBit/bsiOffsetBit``), and runs range
queries as sequential bit-plane scans (reference fragment.go:1271-1534) and
Sum as popcount-per-plane place-value math (reference fragment.go:1130-1138).

Here each kernel takes the magnitude planes as a dense ``uint32[depth, W]``
tensor (LSB plane first) plus ``exists``/``sign``/``filter`` word vectors and
evaluates the whole scan as an unrolled jitted loop over planes — ``depth``
is a static Python int (<= 64), so each (op, depth) pair compiles once and
the plane loop fuses into a handful of vector ops on the VPU.

The kernels are shape-polymorphic over a leading shard axis: pass
``planes[S, depth, W]`` with ``exists/sign/filter[S, W]`` and the same
compiled scan serves a whole stacked field in ONE launch (the executor's
BSI serving stacks), with word-axis reductions kept per shard for
int32-exactness and Min/Max candidate reductions global across shards.

Values are stored as offset-from-base two's-complement-free sign/magnitude:
stored = value - base; sign row holds stored < 0; planes hold abs(stored).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _bound_args(value_abs: int, depth: int):
    """Encode a query bound's magnitude as traced kernel inputs: its low
    ``depth`` bits as a uint32 vector plus an out-of-band flag for
    ``value_abs >= 2^depth``. Keeping the bound traced (not static) means
    each (op, depth, sign-variant) compiles exactly once no matter how many
    distinct bounds a workload queries."""
    bits = jnp.asarray([(value_abs >> k) & 1 for k in range(depth)], jnp.uint32)
    oob = jnp.asarray(value_abs >= (1 << depth))
    return bits, oob


def _select(plane, bit):
    """plane if bit else ~plane, with a traced bit."""
    return jnp.where(bit == 1, plane, ~plane)


@partial(jax.jit, static_argnames=("negative", "depth"))
def _range_eq_kernel(planes, exists, sign, bits, oob, *, negative: bool, depth: int):
    b = exists & (sign if negative else ~sign)
    for k in range(depth):
        b = b & _select(planes[..., k, :], bits[k])
    # A bound outside the representable magnitude can equal nothing.
    return jnp.where(oob, jnp.zeros_like(b), b)


def range_eq(planes, exists, sign, *, value_abs: int, negative: bool, depth: int):
    """Columns whose stored value == ±value_abs (reference fragment.go:1286)."""
    bits, oob = _bound_args(value_abs, depth)
    return _range_eq_kernel(
        planes, exists, sign, bits, oob, negative=negative, depth=depth
    )


def _mag_lt(planes, candidates, bits, oob, depth: int, allow_eq: bool):
    """Among candidates, magnitude < bound (or <= when allow_eq). A bound
    >= 2^depth exceeds every stored magnitude, so all candidates match."""
    lt = jnp.zeros_like(candidates)
    eq = candidates
    for k in reversed(range(depth)):
        p = planes[..., k, :]
        lt = lt | jnp.where(bits[k] == 1, eq & ~p, jnp.zeros_like(eq))
        eq = eq & _select(p, bits[k])
    out = (lt | eq) if allow_eq else lt
    return jnp.where(oob, candidates, out)


def _mag_gt(planes, candidates, bits, oob, depth: int, allow_eq: bool):
    """Among candidates, magnitude > bound (or >= when allow_eq). A bound
    >= 2^depth exceeds every stored magnitude, so nothing matches."""
    gt = jnp.zeros_like(candidates)
    eq = candidates
    for k in reversed(range(depth)):
        p = planes[..., k, :]
        gt = gt | jnp.where(bits[k] == 1, jnp.zeros_like(eq), eq & p)
        eq = eq & _select(p, bits[k])
    out = (gt | eq) if allow_eq else gt
    return jnp.where(oob, jnp.zeros_like(out), out)


@partial(jax.jit, static_argnames=("negative", "depth", "allow_eq"))
def _range_lt_kernel(planes, exists, sign, bits, oob, *, negative, depth, allow_eq):
    neg = exists & sign
    nonneg = exists & ~sign
    if not negative:
        return neg | _mag_lt(planes, nonneg, bits, oob, depth, allow_eq)
    return _mag_gt(planes, neg, bits, oob, depth, allow_eq)


def range_lt(planes, exists, sign, *, value: int, depth: int, allow_eq: bool):
    """Columns with stored value < value (<= when allow_eq).

    Mirrors the sign-split logic of the reference's rangeLT
    (fragment.go:1378-1445): for a non-negative bound all negatives match
    plus non-negatives with small-enough magnitude; for a negative bound
    only negatives with large-enough magnitude match.
    """
    bits, oob = _bound_args(abs(value), depth)
    return _range_lt_kernel(
        planes, exists, sign, bits, oob,
        negative=value < 0, depth=depth, allow_eq=allow_eq,
    )


@partial(jax.jit, static_argnames=("negative", "depth", "allow_eq"))
def _range_gt_kernel(planes, exists, sign, bits, oob, *, negative, depth, allow_eq):
    neg = exists & sign
    nonneg = exists & ~sign
    if not negative:
        return _mag_gt(planes, nonneg, bits, oob, depth, allow_eq)
    return nonneg | _mag_lt(planes, neg, bits, oob, depth, allow_eq)


def range_gt(planes, exists, sign, *, value: int, depth: int, allow_eq: bool):
    """Columns with stored value > value (>= when allow_eq); reference
    fragment.go:1447-1514."""
    bits, oob = _bound_args(abs(value), depth)
    return _range_gt_kernel(
        planes, exists, sign, bits, oob,
        negative=value < 0, depth=depth, allow_eq=allow_eq,
    )


def range_between(planes, exists, sign, *, lo: int, hi: int, depth: int):
    """lo <= stored <= hi (reference fragment.go:1516-1534 rangeBetween)."""
    a = range_gt(planes, exists, sign, value=lo, depth=depth, allow_eq=True)
    b = range_lt(planes, exists, sign, value=hi, depth=depth, allow_eq=True)
    return a & b


@partial(jax.jit, static_argnames=("depth",))
def sum_count(planes, exists, sign, filter_words, *, depth: int):
    """(sum of stored values, count) over filtered columns.

    Place-value popcount per plane, positives minus negatives (reference
    fragment.go:1109-1160). Returns float64-safe int64 math on host side by
    keeping per-plane int32 popcounts; totals are combined in int64 here
    (CPU) / via two int32 halves (TPU handles int64 emulation for scalars).
    """
    f = exists & filter_words
    pos = f & ~sign
    neg = f & sign
    pos_counts = []
    neg_counts = []
    for k in range(depth):
        p = planes[..., k, :]
        # per-(leading-dim) word-axis sums stay int32-exact (<= W*32 per
        # shard); the host combines them in arbitrary precision
        pos_counts.append(
            jnp.sum(lax.population_count(p & pos).astype(jnp.int32), axis=-1)
        )
        neg_counts.append(
            jnp.sum(lax.population_count(p & neg).astype(jnp.int32), axis=-1)
        )
    count = jnp.sum(lax.population_count(f).astype(jnp.int32), axis=-1)
    return (
        jnp.stack(pos_counts) if depth else jnp.zeros((0,), jnp.int32),
        jnp.stack(neg_counts) if depth else jnp.zeros((0,), jnp.int32),
        count,
    )


def sum_host(planes, exists, sign, filter_words, *, depth: int) -> tuple[int, int]:
    """Host wrapper: exact arbitrary-precision (sum, count) from the
    per-plane device popcounts."""

    pos_c, neg_c, count = sum_count(planes, exists, sign, filter_words, depth=depth)
    # ONE pull per tensor (a per-plane loop of np.asarray would pay a
    # host round trip per plane)
    pos_np = np.asarray(pos_c).astype(np.int64)
    neg_np = np.asarray(neg_c).astype(np.int64)
    pos_sums = pos_np.reshape(depth, -1).sum(axis=1) if depth else []
    neg_sums = neg_np.reshape(depth, -1).sum(axis=1) if depth else []
    total = sum(int(c) << k for k, c in enumerate(pos_sums)) - sum(
        int(c) << k for k, c in enumerate(neg_sums)
    )
    return total, int(np.asarray(count).astype(np.int64).sum())


@partial(jax.jit, static_argnames=("depth", "maximal"))
def extreme_mag(planes, candidates, *, depth: int, maximal: bool):
    """(magnitude, surviving-candidate words) of the max (or min) magnitude
    among candidate columns. Empty candidate set returns (0, zeros)."""
    c = candidates
    mag = jnp.zeros((), jnp.int32)
    nonempty = jnp.any(candidates != 0)
    for k in reversed(range(depth)):
        p = planes[..., k, :]
        hit = c & (p if maximal else ~p)
        any_hit = jnp.any(hit != 0)
        c = jnp.where(any_hit, hit, c)
        bit_on = any_hit if maximal else ~any_hit
        mag = mag + jnp.where(bit_on, 1 << k if (1 << k) < 2**31 else 0, 0).astype(mag.dtype)
    return jnp.where(nonempty, mag, 0), c


@partial(jax.jit, static_argnames=("depth", "maximal"))
def _min_max_fused(planes, exists, sign, fw, *, depth: int, maximal: bool):
    """Both sign branches of Min/Max in ONE program: flags, magnitudes,
    counts, and survivor masks.  The host picks the branch from one
    scalar pull instead of issuing a sync per decision (each host sync
    is a full relay round trip on the dev chip)."""
    f = exists & fw
    neg = f & sign
    nonneg = f & ~sign
    # Branch a = preferred: Max prefers non-negatives (largest
    # magnitude), Min prefers negatives; the fallback branch takes the
    # opposite extreme of the magnitude.
    a, b = (nonneg, neg) if maximal else (neg, nonneg)
    mag_a, c_a = extreme_mag(planes, a, depth=depth, maximal=True)
    mag_b, c_b = extreme_mag(planes, b, depth=depth, maximal=False)
    cnt = lambda c: jnp.sum(lax.population_count(c).astype(jnp.int32))
    scalars = jnp.stack(
        [
            jnp.any(a != 0).astype(jnp.int32),
            jnp.any(b != 0).astype(jnp.int32),
            mag_a.astype(jnp.int32),
            cnt(c_a),
            mag_b.astype(jnp.int32),
            cnt(c_b),
        ]
    )
    return scalars, c_a, c_b


def min_max_host(planes, exists, sign, filter_words, *, depth: int, maximal: bool):
    """Host wrapper for Min/Max (reference fragment.go:1152-1225 minUnsigned/
    maxUnsigned + sign handling): returns (stored_value, count) or
    (0, 0) when no column matches.  One launch, one host pull (the
    survivor masks are pulled only for the depth >= 31 exact-magnitude
    recompute)."""
    scalars, c_a, c_b = _min_max_fused(
        jnp.asarray(planes),
        jnp.asarray(exists),
        jnp.asarray(sign),
        jnp.asarray(filter_words),
        depth=depth,
        maximal=maximal,
    )
    has_a, has_b, mag_a, cnt_a, mag_b, cnt_b = (
        np.asarray(scalars).tolist()  # ONE host pull for every decision
    )
    if not has_a and not has_b:
        return 0, 0
    # branch a's sign is + for Max (non-negatives), - for Min (negatives)
    a_positive = maximal
    if has_a:
        value = _exact_mag(planes, c_a, depth, int(mag_a))
        value = value if a_positive else -value
        return value, int(cnt_a)
    value = _exact_mag(planes, c_b, depth, int(mag_b))
    value = -value if a_positive else value
    return value, int(cnt_b)


# ---------------------------------------------------------------------------
# Query-batched kernels: Q range predicates per launch.
#
# The single-query kernels above compile one program per (op, depth,
# sign-variant) and pay a full dispatch per predicate — BENCH_r05 measured
# that overhead drowning the engine (bsi_range_qps 206 vs the CPU path's
# 7,100).  The batched forms lift the traced bound to stacked per-query
# tensors so ONE launch evaluates a whole flight against shared
# ``planes[S, depth, W]``:
#
# * every condition op shares ONE compiled program per (depth, Q-bucket,
#   bound count, need): a query is 1-2 bounds, each encoded as per-plane
#   magnitude-bit word masks plus a meta row of composition masks.  The
#   comparison itself is two LSB-first borrow accumulators per bound —
#   ``A`` (magnitude </<= bound) and ``B`` (magnitude >/>= bound), with
#   strictness folded into the TRACED init word — and the value-space
#   result (sign split, not-null fill, ==/!= via A&B) is selected by
#   traced meta masks.  "<", "><", "!=", "==" are the same program with
#   different traced inputs;
# * a static ``need = (lo, hi)`` pair (a compile key) drops whichever
#   accumulator no bound in the flight reads: a uniform "<=" flight runs
#   one 4-op recurrence per plane instead of the full pair;
# * Q pads to a power of two (padding queries select nothing), so
#   drifting flight sizes reuse the same XLA program.
# ---------------------------------------------------------------------------

_ONES32 = np.uint32(0xFFFFFFFF)
_KSHIFT = np.arange(64)  # plane-index shifts for magnitude-bit expansion
_ZERO_META = [0] * 11    # shared all-zero meta row for padding slots

# comparison ops consumable by encode_query_bounds; "any" is the
# identity bound (matches every existing column).
_CMP_OPS = ("<", "<=", ">", ">=", "==", "!=", "any")

# qmeta channel indices (full-word masks unless noted)
_M_A0 = 0      # lo accumulator init: 0 = strict (<), ONES = non-strict (<=)
_M_B0 = 1      # hi accumulator init: 0 = strict (>), ONES = non-strict (>=)
_M_OOB = 2     # |bound| >= 2^depth: forces A=ONES, B=0
_M_FNEG = 3    # unconditionally include negative columns
_M_FNON = 4    # unconditionally include non-negative columns
_M_SNEG = 5    # apply the compare term to negative columns
_M_SNON = 6    # apply the compare term to non-negative columns
_M_XOR = 7     # invert the compare term (!=)
_M_SELA = 8    # term reads A
_M_SELB = 9    # term reads B
_M_SELC = 10   # term reads A & B (==/!= equality)
_M_CH = 11


def condition_bounds(op: str, value) -> list[tuple[str, int]]:
    """A PQL condition op as 1-2 ``(cmp, stored_bound)`` bounds consumable
    by :func:`encode_query_bounds` (cmp one of ``_CMP_OPS``).  ``value``
    is already base-adjusted (stored space).  ``!= None`` (not-null) is
    the unconditional bound.  Raises ValueError for unsupported shapes."""
    if op == "!=" and value is None:
        return [("any", 0)]
    if op in ("<", "<=", ">", ">=", "==", "!="):
        if value is None:
            raise ValueError(f"condition {op} requires a value")
        return [(op, int(value))]
    if op == "><":
        lo, hi = value
        return [(">=", int(lo)), ("<=", int(hi))]
    if op in ("<x<", "<=x<", "<x<=", "<=x<="):
        lo, hi = value
        lo_op, hi_op = op.split("x")
        return [
            (">=" if lo_op == "<=" else ">", int(lo)),
            ("<=" if hi_op == "<=" else "<", int(hi)),
        ]
    raise ValueError(f"unsupported condition op: {op}")


def encode_query_bounds(queries, depth: int, q_pad: int | None = None):
    """Pack per-query bound lists into the batched kernels' traced inputs:
    ``(qmask[P,B,depth], qinv[P,B,depth], qmeta[P,B,11])`` uint32
    full-word masks (0 / 0xFFFFFFFF), padded to ``q_pad`` queries (padding
    rows select nothing).  ``qmask`` holds the bound magnitude bits as
    per-plane words, ``qinv`` their complement (so the kernels' equality
    term is a single xor), and ``qmeta`` the ``_M_*`` composition
    channels.  Each query is a list of 1-2 ``(cmp, stored_bound)``
    tuples; ``B`` is the flight's max bound count, so an all-single-bound
    flight compiles the cheaper one-scan program.

    Also returns ``need = (lo, hi)``: which borrow accumulators any bound
    in the flight actually reads.  The pair is a compile key — a uniform
    "<="/"<" flight never builds the hi-side recurrence.  Out-of-band
    bounds (``|bound| >= 2^depth``) and the "any" identity read neither:
    their result is decided by the meta masks alone."""
    Q = len(queries)
    P = Q if q_pad is None else q_pad
    if P < Q:
        raise ValueError("q_pad smaller than the query count")
    for bounds in queries:
        if not 1 <= len(bounds) <= 2:
            raise ValueError("each query takes 1-2 bounds")
    B = max((len(b) for b in queries), default=1)
    # stage per-bound scalars in plain python (list sets are ~10x
    # cheaper than numpy scalar assignment at flight sizes), then expand
    # to full-word masks in one vectorized stroke per flight
    mags = [0] * (P * B)
    meta_rows = [_ZERO_META] * (P * B)
    need_lo = need_hi = False
    lim = 1 << depth
    for qi, bounds in enumerate(queries):
        for j in range(B):
            # a missing second bound is the neutral "any" (r & exists)
            cmp_, bound = bounds[j] if j < len(bounds) else ("any", 0)
            meta = [0] * _M_CH
            meta_rows[qi * B + j] = meta
            if cmp_ == "any":
                meta[_M_FNEG] = meta[_M_FNON] = 1
                continue
            if cmp_ not in _CMP_OPS:
                raise ValueError(f"unsupported comparison: {cmp_}")
            mag = abs(int(bound))
            neg = bound < 0
            oob = mag >= lim
            if oob:
                meta[_M_OOB] = 1
            else:
                mags[qi * B + j] = mag
            meta[_M_SNEG if neg else _M_SNON] = 1
            if cmp_ in ("==", "!="):
                meta[_M_A0] = meta[_M_B0] = 1
                meta[_M_SELC] = 1
                if cmp_ == "!=":
                    meta[_M_XOR] = 1
                    meta[_M_FNON if neg else _M_FNEG] = 1
                lo = hi = not oob
            else:
                # value-space </<= of a non-negative bound (or >/>= of a
                # negative one) is the LO side of the magnitude compare;
                # the mirrored cases are the HI side.  The opposite sign
                # class matches unconditionally for </<= nonneg and >/>=
                # neg (fill), and never otherwise.
                lo = (cmp_[0] == "<") != neg
                hi = not lo
                if cmp_.endswith("="):
                    meta[_M_A0 if lo else _M_B0] = 1
                meta[_M_SELA if lo else _M_SELB] = 1
                if cmp_[0] == ("<" if not neg else ">"):
                    meta[_M_FNON if neg else _M_FNEG] = 1
                lo, hi = lo and not oob, hi and not oob
            need_lo = need_lo or lo
            need_hi = need_hi or hi
    # bit k of |bound| -> plane-k word all-ones
    mag_arr = np.asarray(mags, np.int64).reshape(P, B, 1)
    qmask = ((mag_arr >> _KSHIFT[:depth]) & 1).astype(np.uint32) * _ONES32
    qmeta = np.asarray(meta_rows, np.uint32).reshape(P, B, _M_CH) * _ONES32
    qinv = ~qmask
    # padding rows keep qinv = ONES: the accumulators they drag along
    # stay all-zero and the zero meta row selects nothing
    return qmask, qinv, qmeta, (need_lo, need_hi)


def _bound_term(planes, bm, binv, meta, depth: int, need):
    """Compare term for one encoded bound, sign split not yet applied.
    Two LSB-first borrow accumulators walk the planes — ``A`` =
    magnitude </<= bound, ``B`` = magnitude >/>= bound, strictness
    chosen by the traced init words — then the select masks compose
    the ==/!= equality via ``A & B`` and the ``!=`` inversion."""
    shape = planes.shape[:-2] + planes.shape[-1:]
    A = jnp.broadcast_to(meta[_M_A0], shape)
    Bm = jnp.broadcast_to(meta[_M_B0], shape)
    for k in range(depth):  # LSB -> MSB: the last plane dominates
        p = planes[..., k, :]
        x = p ^ bm[k]  # plane bit != bound bit
        # bm & ~p == bm & x and p & ~bm == x & binv, so each side is one
        # xor + and + andnot + or per plane
        if need[0]:
            A = (bm[k] & x) | (A & ~x)
        if need[1]:
            Bm = (x & binv[k]) | (Bm & ~x)
    A = A | meta[_M_OOB]       # oob bound exceeds every magnitude
    Bm = Bm & ~meta[_M_OOB]
    return meta[_M_XOR] ^ (
        (meta[_M_SELA] & A)
        | (meta[_M_SELB] & Bm)
        | (meta[_M_SELC] & A & Bm)
    )


def _bound_eval(planes, neg_cols, nonneg_cols, bm, binv, meta, depth: int, need):
    """Columns matching one encoded bound: the compare term applied to
    its selected sign classes, plus the fill of the opposite class.
    The encoder never fills and selects the SAME sign class, so the two
    halves of the OR are disjoint — count-only callers exploit that."""
    term = _bound_term(planes, bm, binv, meta, depth, need)
    return (
        (meta[_M_FNEG] & neg_cols)
        | (meta[_M_FNON] & nonneg_cols)
        | (((meta[_M_SNEG] & neg_cols) | (meta[_M_SNON] & nonneg_cols)) & term)
    )


def _query_eval(planes, neg_cols, nonneg_cols, mB, iB, tB, depth: int, need):
    r = _bound_eval(planes, neg_cols, nonneg_cols, mB[0], iB[0], tB[0], depth, need)
    for bi in range(1, mB.shape[0]):
        r = r & _bound_eval(
            planes, neg_cols, nonneg_cols, mB[bi], iB[bi], tB[bi], depth, need
        )
    return r


@partial(jax.jit, static_argnames=("depth", "need"))
def _range_batch_kernel(planes, exists, sign, qmask, qinv, qmeta, *, depth: int, need):
    """[Q, ..., W] result masks for Q encoded range predicates in ONE
    launch.  Compile key: (depth, Q-bucket, bound count, need, stack
    shape)."""
    neg_cols = exists & sign
    nonneg_cols = exists & ~sign

    def one(mB, iB, tB):
        return _query_eval(planes, neg_cols, nonneg_cols, mB, iB, tB, depth, need)

    return jax.vmap(one)(qmask, qinv, qmeta)


def _count_one(planes, exists, sign, depth: int, need, n_bounds: int):
    """Per-query count closure shared by the batched count kernels.
    Single-bound flights skip materialising the fill half of the result
    mask: fill and the selected compare classes are disjoint sign
    classes by encoder construction, so the filled class contributes its
    (shared, precomputed) column count as a scalar while only
    ``sel & term`` is popcounted."""
    neg_cols = exists & sign
    nonneg_cols = exists & ~sign
    if n_bounds == 1:
        c_neg = jnp.sum(
            lax.population_count(neg_cols).astype(jnp.int32), axis=-1
        )
        c_non = jnp.sum(
            lax.population_count(nonneg_cols).astype(jnp.int32), axis=-1
        )

        def one(mB, iB, tB):
            meta = tB[0]
            term = _bound_term(planes, mB[0], iB[0], meta, depth, need)
            sel = (meta[_M_SNEG] & neg_cols) | (meta[_M_SNON] & nonneg_cols)
            cnt = jnp.sum(
                lax.population_count(sel & term).astype(jnp.int32), axis=-1
            )
            cnt = cnt + jnp.where(meta[_M_FNEG] != 0, c_neg, 0)
            return cnt + jnp.where(meta[_M_FNON] != 0, c_non, 0)

        return one

    def one(mB, iB, tB):
        r = _query_eval(planes, neg_cols, nonneg_cols, mB, iB, tB, depth, need)
        return jnp.sum(lax.population_count(r).astype(jnp.int32), axis=-1)

    return one


@partial(jax.jit, static_argnames=("depth", "need"))
def _range_count_batch_kernel(planes, exists, sign, qmask, qinv, qmeta, *, depth: int, need):
    """Per-query per-shard match counts ``int32[Q, S]``: vmap over the
    query bucket with the word-axis popcount reduce fused into the same
    launch, so the plane scans of the whole flight compile into one
    elementwise program over the stack (word sums stay int32-exact per
    shard; the host combines in int64)."""
    one = _count_one(planes, exists, sign, depth, need, qmask.shape[1])
    return jax.vmap(one)(qmask, qinv, qmeta)


@partial(jax.jit, static_argnames=("depth", "need"))
def _range_count_scan_kernel(planes, exists, sign, qmask, qinv, qmeta, *, depth: int, need):
    """Scan-over-queries fallback for stacks where the vmap form's
    [Q, S, W] intermediate would not fit comfortably: the working set
    stays one mask wide at the cost of re-reading the planes per query."""
    one = _count_one(planes, exists, sign, depth, need, qmask.shape[1])

    def step(carry, q):
        return carry, one(*q)

    _, counts = lax.scan(step, 0, (qmask, qinv, qmeta))
    return counts


# above this many bytes of [Q-bucket, S, W] flight masks, batched counts
# take the scan kernel (planes re-read per query, but no Q-wide state)
_COUNT_BATCH_VMAP_LIMIT = 256 << 20


def _batch_args(queries, depth: int):
    from pilosa_tpu.ops.bitops import pow2_pad_len

    P = pow2_pad_len(len(queries))
    qmask, qinv, qmeta, need = encode_query_bounds(queries, depth, q_pad=P)
    return (
        jnp.asarray(qmask), jnp.asarray(qinv), jnp.asarray(qmeta),
    ), need


def range_batch(planes, exists, sign, queries, *, depth: int):
    """Batched Range: ``masks[P, ..., W]`` for the encoded ``queries``
    (list of bound lists, see :func:`condition_bounds`); the first
    ``len(queries)`` slices are the per-query results, the pow2-padding
    tail is garbage the caller must ignore."""
    from pilosa_tpu.ops import kernels
    import time

    args = _batch_args(queries, depth)
    t0 = time.perf_counter()
    out = _range_batch_kernel(planes, exists, sign, *args[0], depth=depth, need=args[1])
    kernels.note_bsi_dispatch(
        "bsi_range_batch",
        wall=time.perf_counter() - t0,
        args=(planes, args[0][0]),
        depth=depth,
        q_bucket=int(args[0][0].shape[0]),
        q_useful=len(queries),
    )
    return out


def range_count_batch(planes, exists, sign, queries, *, depth: int):
    """Batched Count(Range): per-query int64 match counts (host-side
    exact sum of the per-shard int32 partials)."""
    from pilosa_tpu.ops import kernels
    import time

    args, need = _batch_args(queries, depth)
    P = int(args[0].shape[0])
    mask_bytes = P * int(np.prod(exists.shape)) * 4
    kern = (
        _range_count_batch_kernel
        if mask_bytes <= _COUNT_BATCH_VMAP_LIMIT
        else _range_count_scan_kernel
    )
    t0 = time.perf_counter()
    counts = kern(planes, exists, sign, *args, depth=depth, need=need)
    kernels.note_bsi_dispatch(
        "bsi_range_count_batch",
        wall=time.perf_counter() - t0,
        args=(planes, args[0]),
        depth=depth,
        q_bucket=P,
        q_useful=len(queries),
    )
    arr = np.asarray(counts).astype(np.int64)
    arr = arr.reshape(arr.shape[0], -1)
    return [int(c) for c in arr.sum(axis=1)[: len(queries)]]


# int32 ceiling for the fused Sum matmul accumulator: per-plane popcounts
# accumulate ACROSS shards on device (unlike sum_count's per-shard
# partials), so the total column count must fit int32.
_SUM_BATCH_ACC_LIMIT = 2**31 - 1


def sum_batch_supported(S: int, W: int) -> bool:
    """Whether the fused batched Sum may accumulate across the whole
    stack in int32 — the `row_counts_supported`-style decline gate;
    callers fall back to the per-query host lane."""
    return S * W * 32 <= _SUM_BATCH_ACC_LIMIT


@jax.jit
def _sum_batch_kernel(planes, exists, sign, filters):
    """Fused popcount-reduction Sum over Q filters: gram-style int8
    unpack + MXU matmul of [depth+1 rows] x [2Q filter rows] per shard,
    accumulated over the shard scan — one launch answers every (plane,
    filter, sign-class) popcount the place-value combine needs.
    ``filters`` is ``uint32[S, Q, W]``; returns ``int32[depth+1, 2Q]``
    (positive columns first, then negative; row depth = exists counts)."""
    from pilosa_tpu.ops.kernels import _unpack_int8

    f = filters & exists[:, None, :]
    fpos = f & ~sign[:, None, :]
    fneg = f & sign[:, None, :]
    filt2 = jnp.concatenate([fpos, fneg], axis=1)  # [S, 2Q, W]
    rows = jnp.concatenate([planes, exists[:, None, :]], axis=1)

    def body(acc, sf):
        r, ff = sf
        g = lax.dot_general(
            _unpack_int8(r), _unpack_int8(ff),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return acc + g, None

    acc0 = jnp.zeros((rows.shape[1], filt2.shape[1]), jnp.int32)
    acc, _ = lax.scan(body, acc0, (rows, filt2))
    return acc


def sum_batch_host(planes, exists, sign, filters, *, depth: int):
    """Batched Sum host wrapper: ``[(sum, count), ...]`` per filter row.
    ``filters`` is ``uint32[S, Q, W]`` (pass ``exists`` slices for
    unfiltered queries); place-value combine in python ints so totals
    past 2^63 stay exact."""
    from pilosa_tpu.ops import kernels
    import time

    Q = int(filters.shape[1])
    t0 = time.perf_counter()
    acc = _sum_batch_kernel(planes, exists, sign, filters)
    kernels.note_bsi_dispatch(
        "bsi_sum_batch",
        wall=time.perf_counter() - t0,
        args=(planes, filters),
        depth=depth,
        q_bucket=Q,
        q_useful=Q,
    )
    acc = np.asarray(acc).astype(np.int64)  # [depth+1, 2Q]
    out = []
    for q in range(Q):
        pos, neg = acc[:, q], acc[:, Q + q]
        total = sum(int(pos[k]) << k for k in range(depth)) - sum(
            int(neg[k]) << k for k in range(depth)
        )
        out.append((total, int(pos[depth]) + int(neg[depth])))
    return out


def _exact_mag(planes, survivors, depth: int, approx: int) -> int:
    """extreme_mag tracks magnitude in int32; for depth >= 31 recompute the
    exact magnitude from one surviving column on the host."""
    if depth < 31:
        return approx

    surv = np.asarray(survivors)
    s = None
    if surv.ndim == 2:  # stacked [S, W]: locate one surviving shard first
        s_idx = np.flatnonzero(surv.any(axis=1))
        if len(s_idx) == 0:
            return 0
        s = int(s_idx[0])
        surv = surv[s]
    idx = np.flatnonzero(np.unpackbits(surv.view(np.uint8), bitorder="little"))
    if len(idx) == 0:
        return 0
    col = int(idx[0])
    w, b = col >> 5, col & 31
    # slice the one surviving column's plane words device-side — pulling
    # the whole planes tensor would transfer the full field per query
    pl_col = np.asarray(planes[s, :, w] if s is not None else planes[:, w])
    mag = 0
    for k in range(depth):
        if (int(pl_col[k]) >> b) & 1:
            mag |= 1 << k
    return mag
