"""Bit-sliced-index (BSI) kernels.

The reference stores integer fields bit-sliced: row 0 = exists bit, row 1 =
sign bit, rows 2..2+bitDepth = magnitude bit-planes (reference
fragment.go:90-96 ``bsiExistsBit/bsiSignBit/bsiOffsetBit``), and runs range
queries as sequential bit-plane scans (reference fragment.go:1271-1534) and
Sum as popcount-per-plane place-value math (reference fragment.go:1130-1138).

Here each kernel takes the magnitude planes as a dense ``uint32[depth, W]``
tensor (LSB plane first) plus ``exists``/``sign``/``filter`` word vectors and
evaluates the whole scan as an unrolled jitted loop over planes — ``depth``
is a static Python int (<= 64), so each (op, depth) pair compiles once and
the plane loop fuses into a handful of vector ops on the VPU.

The kernels are shape-polymorphic over a leading shard axis: pass
``planes[S, depth, W]`` with ``exists/sign/filter[S, W]`` and the same
compiled scan serves a whole stacked field in ONE launch (the executor's
BSI serving stacks), with word-axis reductions kept per shard for
int32-exactness and Min/Max candidate reductions global across shards.

Values are stored as offset-from-base two's-complement-free sign/magnitude:
stored = value - base; sign row holds stored < 0; planes hold abs(stored).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _bound_args(value_abs: int, depth: int):
    """Encode a query bound's magnitude as traced kernel inputs: its low
    ``depth`` bits as a uint32 vector plus an out-of-band flag for
    ``value_abs >= 2^depth``. Keeping the bound traced (not static) means
    each (op, depth, sign-variant) compiles exactly once no matter how many
    distinct bounds a workload queries."""
    bits = jnp.asarray([(value_abs >> k) & 1 for k in range(depth)], jnp.uint32)
    oob = jnp.asarray(value_abs >= (1 << depth))
    return bits, oob


def _select(plane, bit):
    """plane if bit else ~plane, with a traced bit."""
    return jnp.where(bit == 1, plane, ~plane)


@partial(jax.jit, static_argnames=("negative", "depth"))
def _range_eq_kernel(planes, exists, sign, bits, oob, *, negative: bool, depth: int):
    b = exists & (sign if negative else ~sign)
    for k in range(depth):
        b = b & _select(planes[..., k, :], bits[k])
    # A bound outside the representable magnitude can equal nothing.
    return jnp.where(oob, jnp.zeros_like(b), b)


def range_eq(planes, exists, sign, *, value_abs: int, negative: bool, depth: int):
    """Columns whose stored value == ±value_abs (reference fragment.go:1286)."""
    bits, oob = _bound_args(value_abs, depth)
    return _range_eq_kernel(
        planes, exists, sign, bits, oob, negative=negative, depth=depth
    )


def _mag_lt(planes, candidates, bits, oob, depth: int, allow_eq: bool):
    """Among candidates, magnitude < bound (or <= when allow_eq). A bound
    >= 2^depth exceeds every stored magnitude, so all candidates match."""
    lt = jnp.zeros_like(candidates)
    eq = candidates
    for k in reversed(range(depth)):
        p = planes[..., k, :]
        lt = lt | jnp.where(bits[k] == 1, eq & ~p, jnp.zeros_like(eq))
        eq = eq & _select(p, bits[k])
    out = (lt | eq) if allow_eq else lt
    return jnp.where(oob, candidates, out)


def _mag_gt(planes, candidates, bits, oob, depth: int, allow_eq: bool):
    """Among candidates, magnitude > bound (or >= when allow_eq). A bound
    >= 2^depth exceeds every stored magnitude, so nothing matches."""
    gt = jnp.zeros_like(candidates)
    eq = candidates
    for k in reversed(range(depth)):
        p = planes[..., k, :]
        gt = gt | jnp.where(bits[k] == 1, jnp.zeros_like(eq), eq & p)
        eq = eq & _select(p, bits[k])
    out = (gt | eq) if allow_eq else gt
    return jnp.where(oob, jnp.zeros_like(out), out)


@partial(jax.jit, static_argnames=("negative", "depth", "allow_eq"))
def _range_lt_kernel(planes, exists, sign, bits, oob, *, negative, depth, allow_eq):
    neg = exists & sign
    nonneg = exists & ~sign
    if not negative:
        return neg | _mag_lt(planes, nonneg, bits, oob, depth, allow_eq)
    return _mag_gt(planes, neg, bits, oob, depth, allow_eq)


def range_lt(planes, exists, sign, *, value: int, depth: int, allow_eq: bool):
    """Columns with stored value < value (<= when allow_eq).

    Mirrors the sign-split logic of the reference's rangeLT
    (fragment.go:1378-1445): for a non-negative bound all negatives match
    plus non-negatives with small-enough magnitude; for a negative bound
    only negatives with large-enough magnitude match.
    """
    bits, oob = _bound_args(abs(value), depth)
    return _range_lt_kernel(
        planes, exists, sign, bits, oob,
        negative=value < 0, depth=depth, allow_eq=allow_eq,
    )


@partial(jax.jit, static_argnames=("negative", "depth", "allow_eq"))
def _range_gt_kernel(planes, exists, sign, bits, oob, *, negative, depth, allow_eq):
    neg = exists & sign
    nonneg = exists & ~sign
    if not negative:
        return _mag_gt(planes, nonneg, bits, oob, depth, allow_eq)
    return nonneg | _mag_lt(planes, neg, bits, oob, depth, allow_eq)


def range_gt(planes, exists, sign, *, value: int, depth: int, allow_eq: bool):
    """Columns with stored value > value (>= when allow_eq); reference
    fragment.go:1447-1514."""
    bits, oob = _bound_args(abs(value), depth)
    return _range_gt_kernel(
        planes, exists, sign, bits, oob,
        negative=value < 0, depth=depth, allow_eq=allow_eq,
    )


def range_between(planes, exists, sign, *, lo: int, hi: int, depth: int):
    """lo <= stored <= hi (reference fragment.go:1516-1534 rangeBetween)."""
    a = range_gt(planes, exists, sign, value=lo, depth=depth, allow_eq=True)
    b = range_lt(planes, exists, sign, value=hi, depth=depth, allow_eq=True)
    return a & b


@partial(jax.jit, static_argnames=("depth",))
def sum_count(planes, exists, sign, filter_words, *, depth: int):
    """(sum of stored values, count) over filtered columns.

    Place-value popcount per plane, positives minus negatives (reference
    fragment.go:1109-1160). Returns float64-safe int64 math on host side by
    keeping per-plane int32 popcounts; totals are combined in int64 here
    (CPU) / via two int32 halves (TPU handles int64 emulation for scalars).
    """
    f = exists & filter_words
    pos = f & ~sign
    neg = f & sign
    pos_counts = []
    neg_counts = []
    for k in range(depth):
        p = planes[..., k, :]
        # per-(leading-dim) word-axis sums stay int32-exact (<= W*32 per
        # shard); the host combines them in arbitrary precision
        pos_counts.append(
            jnp.sum(lax.population_count(p & pos).astype(jnp.int32), axis=-1)
        )
        neg_counts.append(
            jnp.sum(lax.population_count(p & neg).astype(jnp.int32), axis=-1)
        )
    count = jnp.sum(lax.population_count(f).astype(jnp.int32), axis=-1)
    return (
        jnp.stack(pos_counts) if depth else jnp.zeros((0,), jnp.int32),
        jnp.stack(neg_counts) if depth else jnp.zeros((0,), jnp.int32),
        count,
    )


def sum_host(planes, exists, sign, filter_words, *, depth: int) -> tuple[int, int]:
    """Host wrapper: exact arbitrary-precision (sum, count) from the
    per-plane device popcounts."""

    pos_c, neg_c, count = sum_count(planes, exists, sign, filter_words, depth=depth)
    # ONE pull per tensor (a per-plane loop of np.asarray would pay a
    # host round trip per plane)
    pos_np = np.asarray(pos_c).astype(np.int64)
    neg_np = np.asarray(neg_c).astype(np.int64)
    pos_sums = pos_np.reshape(depth, -1).sum(axis=1) if depth else []
    neg_sums = neg_np.reshape(depth, -1).sum(axis=1) if depth else []
    total = sum(int(c) << k for k, c in enumerate(pos_sums)) - sum(
        int(c) << k for k, c in enumerate(neg_sums)
    )
    return total, int(np.asarray(count).astype(np.int64).sum())


@partial(jax.jit, static_argnames=("depth", "maximal"))
def extreme_mag(planes, candidates, *, depth: int, maximal: bool):
    """(magnitude, surviving-candidate words) of the max (or min) magnitude
    among candidate columns. Empty candidate set returns (0, zeros)."""
    c = candidates
    mag = jnp.zeros((), jnp.int32)
    nonempty = jnp.any(candidates != 0)
    for k in reversed(range(depth)):
        p = planes[..., k, :]
        hit = c & (p if maximal else ~p)
        any_hit = jnp.any(hit != 0)
        c = jnp.where(any_hit, hit, c)
        bit_on = any_hit if maximal else ~any_hit
        mag = mag + jnp.where(bit_on, 1 << k if (1 << k) < 2**31 else 0, 0).astype(mag.dtype)
    return jnp.where(nonempty, mag, 0), c


@partial(jax.jit, static_argnames=("depth", "maximal"))
def _min_max_fused(planes, exists, sign, fw, *, depth: int, maximal: bool):
    """Both sign branches of Min/Max in ONE program: flags, magnitudes,
    counts, and survivor masks.  The host picks the branch from one
    scalar pull instead of issuing a sync per decision (each host sync
    is a full relay round trip on the dev chip)."""
    f = exists & fw
    neg = f & sign
    nonneg = f & ~sign
    # Branch a = preferred: Max prefers non-negatives (largest
    # magnitude), Min prefers negatives; the fallback branch takes the
    # opposite extreme of the magnitude.
    a, b = (nonneg, neg) if maximal else (neg, nonneg)
    mag_a, c_a = extreme_mag(planes, a, depth=depth, maximal=True)
    mag_b, c_b = extreme_mag(planes, b, depth=depth, maximal=False)
    cnt = lambda c: jnp.sum(lax.population_count(c).astype(jnp.int32))
    scalars = jnp.stack(
        [
            jnp.any(a != 0).astype(jnp.int32),
            jnp.any(b != 0).astype(jnp.int32),
            mag_a.astype(jnp.int32),
            cnt(c_a),
            mag_b.astype(jnp.int32),
            cnt(c_b),
        ]
    )
    return scalars, c_a, c_b


def min_max_host(planes, exists, sign, filter_words, *, depth: int, maximal: bool):
    """Host wrapper for Min/Max (reference fragment.go:1152-1225 minUnsigned/
    maxUnsigned + sign handling): returns (stored_value, count) or
    (0, 0) when no column matches.  One launch, one host pull (the
    survivor masks are pulled only for the depth >= 31 exact-magnitude
    recompute)."""
    scalars, c_a, c_b = _min_max_fused(
        jnp.asarray(planes),
        jnp.asarray(exists),
        jnp.asarray(sign),
        jnp.asarray(filter_words),
        depth=depth,
        maximal=maximal,
    )
    has_a, has_b, mag_a, cnt_a, mag_b, cnt_b = (
        np.asarray(scalars).tolist()  # ONE host pull for every decision
    )
    if not has_a and not has_b:
        return 0, 0
    # branch a's sign is + for Max (non-negatives), - for Min (negatives)
    a_positive = maximal
    if has_a:
        value = _exact_mag(planes, c_a, depth, int(mag_a))
        value = value if a_positive else -value
        return value, int(cnt_a)
    value = _exact_mag(planes, c_b, depth, int(mag_b))
    value = -value if a_positive else value
    return value, int(cnt_b)


def _exact_mag(planes, survivors, depth: int, approx: int) -> int:
    """extreme_mag tracks magnitude in int32; for depth >= 31 recompute the
    exact magnitude from one surviving column on the host."""
    if depth < 31:
        return approx

    surv = np.asarray(survivors)
    s = None
    if surv.ndim == 2:  # stacked [S, W]: locate one surviving shard first
        s_idx = np.flatnonzero(surv.any(axis=1))
        if len(s_idx) == 0:
            return 0
        s = int(s_idx[0])
        surv = surv[s]
    idx = np.flatnonzero(np.unpackbits(surv.view(np.uint8), bitorder="little"))
    if len(idx) == 0:
        return 0
    col = int(idx[0])
    w, b = col >> 5, col & 31
    # slice the one surviving column's plane words device-side — pulling
    # the whole planes tensor would transfer the full field per query
    pl_col = np.asarray(planes[s, :, w] if s is not None else planes[:, w])
    mag = 0
    for k in range(depth):
        if (int(pl_col[k]) >> b) & 1:
            mag |= 1 << k
    return mag
