"""ctypes bindings for the host latency-tier kernels (native/hostops.cpp).

Built on demand through the shared loader (pilosa_tpu/nativelib.py);
every entry point degrades to numpy (``np.bitwise_count``) when no
toolchain exists.  Set ``PILOSA_TPU_NO_NATIVE=1`` to force the numpy
path.
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

from pilosa_tpu import nativelib

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "hostops.cpp",
)
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "libpilosa_hostops.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_U8P = ctypes.POINTER(ctypes.c_uint8)
_U64P = ctypes.POINTER(ctypes.c_uint64)

# PQL set-op name -> native op code (native/hostops.cpp enum Op)
OP_CODES = {"intersect": 0, "union": 1, "difference": 2, "xor": 3}


_I64P = ctypes.POINTER(ctypes.c_int64)


def _bind(lib: ctypes.CDLL) -> None:
    lib.ph_popcount.restype = ctypes.c_uint64
    lib.ph_popcount.argtypes = [_U8P, ctypes.c_size_t]
    lib.ph_import_merge.restype = ctypes.c_int64
    lib.ph_import_merge.argtypes = [
        _I64P, ctypes.c_size_t, ctypes.c_int64, ctypes.c_int64,
        _I64P, _U64P, ctypes.c_size_t, ctypes.c_int, _U8P, ctypes.c_int,
        _U64P, _I64P, _I64P, _I64P,
    ]
    lib.ph_pair_count.restype = ctypes.c_uint64
    lib.ph_pair_count.argtypes = [
        _U8P, _U8P, ctypes.c_size_t, ctypes.c_int,
    ]
    lib.ph_pair_op.restype = None
    lib.ph_pair_op.argtypes = [
        _U8P, _U8P, _U8P, ctypes.c_size_t, ctypes.c_int,
    ]
    lib.ph_extract.restype = ctypes.c_size_t
    lib.ph_extract.argtypes = [_U8P, ctypes.c_size_t, ctypes.c_uint64, _U64P]
    lib.ph_pair_count_addr.restype = ctypes.c_uint64
    lib.ph_pair_count_addr.argtypes = [
        _U64P, _U64P, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_int,
    ]


def load() -> ctypes.CDLL | None:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        _lib = nativelib.load(_SRC, _LIB_PATH, _bind)
        return _lib


def _u8(a: np.ndarray):
    return a.ctypes.data_as(_U8P)


def popcount(words: np.ndarray) -> int:
    """Total set bits of a C-contiguous uint32 array (any shape)."""
    lib = load()
    words = np.ascontiguousarray(words, dtype=np.uint32)
    if lib is None:
        return int(np.bitwise_count(words).sum(dtype=np.uint64))
    return int(lib.ph_popcount(_u8(words), words.size))


def pair_count(a: np.ndarray, b: np.ndarray, op: str) -> int:
    """Fused ``popcount(op(a, b))`` without materializing the op —
    the host twin of ops/bitops.py's jitted *_count kernels (reference
    roaring.go:568)."""
    lib = load()
    a = np.ascontiguousarray(a, dtype=np.uint32)
    b = np.ascontiguousarray(b, dtype=np.uint32)
    if a.size != b.size:
        raise ValueError("pair_count operands differ in size")
    if lib is None:
        if op == "intersect":
            x = a & b
        elif op == "union":
            x = a | b
        elif op == "difference":
            x = a & ~b
        else:
            x = a ^ b
        return int(np.bitwise_count(x).sum(dtype=np.uint64))
    return int(lib.ph_pair_count(_u8(a), _u8(b), a.size, OP_CODES[op]))


def pair_count_addrs(
    addr_a: np.ndarray, addr_b: np.ndarray, n_words: int, op: str
) -> int | None:
    """Sum of fused pair counts over rows given by ABSOLUTE addresses
    (uint64 numpy arrays) — the zero-marshalling latency-tier entry:
    the caller computes ``base + slot*stride`` vectorized and this
    makes one ctypes crossing for the whole shard fan.  The caller owns
    keeping the backing arrays alive and locked for the duration.
    None when no native library is available."""
    lib = load()
    if lib is None:
        return None
    addr_a = np.ascontiguousarray(addr_a, dtype=np.uint64)
    addr_b = np.ascontiguousarray(addr_b, dtype=np.uint64)
    return int(
        lib.ph_pair_count_addr(
            addr_a.ctypes.data_as(_U64P),
            addr_b.ctypes.data_as(_U64P),
            addr_a.size, n_words, OP_CODES[op],
        )
    )


def extract_positions(words: np.ndarray, base: int = 0) -> np.ndarray | None:
    """Set-bit offsets (+ ``base``) of a contiguous uint32 word vector,
    ascending — the ctz walk behind snapshot encoding; None when no
    native library is available (callers keep their numpy path)."""
    lib = load()
    if lib is None:
        return None
    words = np.ascontiguousarray(words, dtype=np.uint32)
    n = int(lib.ph_popcount(_u8(words), words.size))
    out = np.empty(n, dtype=np.uint64)
    k = lib.ph_extract(
        _u8(words), words.size, ctypes.c_uint64(base),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return out[:k]


def import_merge(
    keys: np.ndarray,
    width: int,
    n_words: int,
    slots: np.ndarray,
    row_ids: np.ndarray,
    mirror: np.ndarray,
    clear: bool,
    id_keys: bool = False,
    want_wal: bool = True,
) -> tuple[int, np.ndarray | None, np.ndarray, np.ndarray] | None:
    """One native pass over SORTED keys (``row_index*width + col``, or
    ``row_id*width + col`` with ``id_keys=True``; duplicates allowed):
    apply the bulk set/clear to ``mirror`` (uint32 [capacity, n_words],
    mutated in place) and return
    ``(n_changed, wal_positions, perrow_changed, changed_word_indices)``
    — everything Fragment.import_bits needs after the merge.  None when
    no native library is available (callers keep their numpy path).
    ``want_wal=False`` skips the WAL-position extraction (and its
    keys.size allocation) — store-less fragments have no op log to
    feed, and the ingest pipeline's merged applies make that array the
    largest allocation of the whole pass.  The caller owns key bounds
    and holds the fragment lock."""
    lib = load()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    slots = np.ascontiguousarray(slots, dtype=np.int64)
    row_ids = np.ascontiguousarray(row_ids, dtype=np.uint64)
    wal = np.empty(keys.size, dtype=np.uint64) if want_wal else None
    perrow = np.zeros(slots.size, dtype=np.int64)
    cw = np.empty(keys.size, dtype=np.int64)
    ncw = np.zeros(1, dtype=np.int64)
    nc = int(
        lib.ph_import_merge(
            keys.ctypes.data_as(_I64P), keys.size, width, n_words,
            slots.ctypes.data_as(_I64P),
            row_ids.ctypes.data_as(_U64P), row_ids.size, int(id_keys),
            _u8(mirror), int(clear),
            wal.ctypes.data_as(_U64P) if wal is not None else None,
            perrow.ctypes.data_as(_I64P),
            cw.ctypes.data_as(_I64P),
            ncw.ctypes.data_as(_I64P),
        )
    )
    return nc, wal[:nc] if wal is not None else None, perrow, cw[: int(ncw[0])]


def pair_op(a: np.ndarray, b: np.ndarray, op: str) -> np.ndarray:
    """Materialized ``op(a, b)`` into a fresh array (numpy-compatible
    semantics, native single pass)."""
    lib = load()
    a = np.ascontiguousarray(a, dtype=np.uint32)
    b = np.ascontiguousarray(b, dtype=np.uint32)
    if lib is None:
        if op == "intersect":
            return a & b
        if op == "union":
            return a | b
        if op == "difference":
            return a & ~b
        return a ^ b
    out = np.empty_like(a)
    lib.ph_pair_op(_u8(a), _u8(b), _u8(out), a.size, OP_CODES[op])
    return out
