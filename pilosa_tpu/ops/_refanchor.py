"""ctypes bindings for the reference-anchor library
(native/refanchor.cpp): a compiled port of the semantic work of the
reference's hot benchmark paths (roaring containers, AddN, CountRange,
intersectionCount, snapshot serialization), used as the measured
comparison baseline in bench.py / tools/ref_anchor.py.

Built on demand through the shared loader (pilosa_tpu/nativelib.py);
``load()`` returns None when no toolchain exists — callers must skip
the anchor then (there is no Python fallback: an interpreted anchor
would flatter the repo's numbers, which defeats its purpose).
"""

from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

from pilosa_tpu import nativelib

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "refanchor.cpp",
)
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "libpilosa_refanchor.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_U64P = ctypes.POINTER(ctypes.c_uint64)


def _bind(lib: ctypes.CDLL) -> None:
    lib.ra_new.restype = ctypes.c_void_p
    lib.ra_new.argtypes = []
    lib.ra_free.restype = None
    lib.ra_free.argtypes = [ctypes.c_void_p]
    lib.ra_addn_sorted.restype = ctypes.c_uint64
    lib.ra_addn_sorted.argtypes = [ctypes.c_void_p, _U64P, ctypes.c_size_t]
    lib.ra_count_range.restype = ctypes.c_uint64
    lib.ra_count_range.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
    ]
    lib.ra_intersection_count.restype = ctypes.c_uint64
    lib.ra_intersection_count.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
    ]
    lib.ra_intersection_count_many.restype = ctypes.c_uint64
    lib.ra_intersection_count_many.argtypes = [
        ctypes.c_void_p, _U64P, _U64P, ctypes.c_size_t, ctypes.c_uint64,
    ]
    lib.ra_snapshot.restype = ctypes.c_int64
    lib.ra_snapshot.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ra_count.restype = ctypes.c_uint64
    lib.ra_count.argtypes = [ctypes.c_void_p]


def load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        _lib = nativelib.load(_SRC, _LIB_PATH, _bind)
        return _lib


class RefBitmap:
    """A reference-semantics roaring bitmap handle."""

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError("refanchor library unavailable")
        self._lib = lib
        self._h = lib.ra_new()

    def close(self) -> None:
        if self._h:
            self._lib.ra_free(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def addn_sorted(self, positions: np.ndarray) -> int:
        """Bulk-add sorted, deduped uint64 positions; changed count."""
        positions = np.ascontiguousarray(positions, dtype=np.uint64)
        return int(
            self._lib.ra_addn_sorted(
                self._h, positions.ctypes.data_as(_U64P), positions.size
            )
        )

    def count_range(self, lo: int, hi: int) -> int:
        return int(self._lib.ra_count_range(self._h, lo, hi))

    def intersection_count(self, row_a: int, row_b: int, shard_width: int) -> int:
        return int(
            self._lib.ra_intersection_count(self._h, row_a, row_b, shard_width)
        )

    def intersection_count_many(
        self, rows_a: np.ndarray, rows_b: np.ndarray, shard_width: int
    ) -> int:
        """Sum of per-pair intersection counts in ONE native crossing
        (the reference fans shards in-process; per-pair ctypes calls
        would bias the anchor slow)."""
        rows_a = np.ascontiguousarray(rows_a, dtype=np.uint64)
        rows_b = np.ascontiguousarray(rows_b, dtype=np.uint64)
        return int(
            self._lib.ra_intersection_count_many(
                self._h,
                rows_a.ctypes.data_as(_U64P),
                rows_b.ctypes.data_as(_U64P),
                rows_a.size,
                shard_width,
            )
        )

    def snapshot(self, path: str) -> int:
        n = int(self._lib.ra_snapshot(self._h, path.encode()))
        if n < 0:
            raise OSError(f"refanchor snapshot failed: {path}")
        return n

    def count(self) -> int:
        return int(self._lib.ra_count(self._h))
