"""Distribution over TPU device meshes.

The reference distributes fragments across nodes by jump-hash over an HTTP
cluster and fans queries out as goroutine map-reduce (reference
cluster.go:922-934, executor.go:2454-2611). Here the same shard axis maps
onto a ``jax.sharding.Mesh`` axis: fragments stack into
``uint32[shards, rows, words]`` tensors laid out with ``NamedSharding``,
queries compile once with pjit and XLA inserts the ICI collectives for the
reduce step (psum of per-shard counts, all-gather of row slices across a
row-sharded axis)."""

from pilosa_tpu.parallel.mesh import default_mesh, mesh_shape_for
from pilosa_tpu.parallel.sharded import ShardedField

__all__ = ["default_mesh", "mesh_shape_for", "ShardedField"]
