"""Device mesh construction.

One mesh axis covers this workload's parallelism (SURVEY §2.3):

* ``shards`` — the data-parallel axis: columns striped into 2^20-wide
  shards, each device slice owning a contiguous set of shards (the
  analogue of the reference's shard→node jump-hash placement,
  cluster.go:858-934, made static because TPU meshes are static).

A second ``rows`` (tensor-parallel-style) axis existed through round 4
but was DELIBERATELY collapsed (r05): every serving kernel's work is
embarrassingly parallel along shards, so whenever the index has at
least as many shards as the mesh has devices — the regime this design
targets — an all-``shards`` split gives the identical per-device FLOP
count with ZERO cross-device gathers, while a rows split forces a
row-block all-gather into every pair/gram kernel.  Splitting rows only
pays when shards < devices (a tiny index on a large pod), which the
stacked layout handles anyway by padding the shard axis.  The axis name
is kept in ``default_mesh`` signatures (size 1) so ShardedField's
specs stay stable.

The CLUSTER layer rides this same mesh: nodes whose holders live in
this process register in ``parallel/meshplace.py``, and
``cluster/dist.py`` then plans their shard groups into one jit-sharded
launch over ``serving_mesh()`` instead of an HTTP relay — the cluster
disappears into the mesh (docs/serving.md "Cluster on the mesh")."""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def mesh_shape_for(n_devices: int) -> tuple[int, int]:
    """(shards, rows) axis sizes — all devices on the ``shards`` axis
    (see the module docstring for why the rows factor was dropped)."""
    return n_devices, 1


def default_mesh(n_devices: int | None = None, axis_names=("shards", "rows")) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    s, r = mesh_shape_for(len(devices))
    return Mesh(np.array(devices).reshape(s, r), axis_names)


_serving_mesh: Mesh | None = None
_serving_max_devices: int | None = None


def configure_serving(max_devices: int | None) -> None:
    """Cap the serving mesh at the first ``max_devices`` devices (None =
    all). The analogue of the reference's cluster-size config; also lets
    a dryrun model an exact device count on a larger virtual backend."""
    global _serving_max_devices, _serving_mesh
    _serving_max_devices = max_devices
    _serving_mesh = None


def serving_mesh() -> Mesh | None:
    """1-D ``("shards",)`` mesh over the visible devices, used by the
    serving executor's field stacks so each device owns a contiguous
    slice of shards — the reference's shard→node placement
    (cluster.go:858-934) made static. None on a single-device host (the
    plain single-device path is faster than a degenerate mesh)."""
    global _serving_mesh
    # local_devices, not devices: each process serves the shards it owns
    # (the cluster layer routes cross-host queries); a mesh spanning
    # non-addressable devices would make device_put raise mid-query.
    devices = jax.local_devices()
    if _serving_max_devices is not None:
        devices = devices[:_serving_max_devices]
    if len(devices) <= 1:
        return None
    if _serving_mesh is None or list(_serving_mesh.devices.flat) != devices:
        _serving_mesh = Mesh(np.array(devices), ("shards",))
    return _serving_mesh


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> Mesh:
    """Join this process to a multi-host JAX job and return the global
    mesh over every host's devices.

    The reference scales across hosts with memberlist gossip + HTTP RPC
    (SURVEY §2.4); the TPU-native data plane instead uses the JAX
    distributed runtime: one coordinator process, XLA collectives riding
    ICI within a slice and DCN across slices. The ``shards`` axis is laid
    out so consecutive shards land on one host's devices first — keeping
    the reduce step of a multi-shard query on ICI, with only the final
    cross-host combine touching DCN.

    Args default from the standard JAX env (JAX_COORDINATOR_ADDRESS etc.)
    when omitted; on a single-host job this degrades to ``default_mesh``.
    The cluster layer (HTTP membership, resize, anti-entropy) still runs
    per-host for storage ownership — this function only wires the
    device-compute plane.
    """
    import os

    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif os.environ.get("JAX_COORDINATOR_ADDRESS") and jax.process_count() == 1:
        jax.distributed.initialize()
    return default_mesh()
