"""Device mesh construction.

Two mesh axes cover this workload's parallelism inventory (SURVEY §2.3):

* ``shards`` — the data-parallel axis: columns striped into 2^20-wide
  shards, each device slice owning a contiguous set of shards (the
  analogue of the reference's shard→node jump-hash placement,
  cluster.go:858-934, made static because TPU meshes are static).
* ``rows`` — the tensor-parallel-style axis: a fragment's row dimension
  split across devices, so row-count scans (TopN/GroupBy) and BSI
  plane walks parallelize within one shard.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh


def mesh_shape_for(n_devices: int) -> tuple[int, int]:
    """(shards, rows) axis sizes: prefer sharding columns; give the row
    axis a factor of 2 when the device count allows."""
    if n_devices % 2 == 0 and n_devices > 2:
        return n_devices // 2, 2
    return n_devices, 1


def default_mesh(n_devices: int | None = None, axis_names=("shards", "rows")) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    s, r = mesh_shape_for(len(devices))
    return Mesh(np.array(devices).reshape(s, r), axis_names)
