"""Device-placement map: which cluster nodes' shards are slices of the
local serving mesh.

The scale-out story (docs/serving.md "Cluster on the mesh") needs the
cluster layer to know, per owner node, whether that node's fragments are
directly addressable from this process — i.e. whether its shards live on
the same accelerator mesh the serving executor launches over.  When they
are, ``cluster/dist.py`` plans those shards into a mesh-local partition
(one jit-sharded launch, collective reduction) instead of an HTTP relay.

A node advertises itself by registering its holder here on ``start()``
and withdrawing on ``stop()`` (server/node.py).  In production — one
process per host — only the local node ever registers, so the registry
is a no-op and every peer stays on the HTTP fan-out.  In an
``InProcessCluster`` (tests, bench, a future one-process-many-chips
deployment) every member registers, so the whole cluster collapses onto
the mesh.

This is deliberately process-global rather than per-cluster: being in
the same process IS the locality property that makes a peer's fragments
mesh-addressable, and node ids are unique across live in-process
clusters (uuid-suffixed in testing.cluster).
"""

from __future__ import annotations

import itertools
import os
import threading


class MeshHandle:
    """One registered node: its holder plus a generation stamp that
    changes on every (re-)registration, so placement-keyed executor
    caches invalidate when a node restarts with a fresh holder."""

    __slots__ = ("node_id", "holder", "generation")

    def __init__(self, node_id: str, holder, generation: int):
        self.node_id = node_id
        self.holder = holder
        self.generation = generation


class MeshPlacement:
    def __init__(self):
        self._lock = threading.Lock()
        self._handles: dict[str, MeshHandle] = {}
        self._gen = itertools.count(1)

    def register(self, node_id: str, holder) -> None:
        with self._lock:
            self._handles[node_id] = MeshHandle(node_id, holder, next(self._gen))

    def unregister(self, node_id: str) -> None:
        with self._lock:
            self._handles.pop(node_id, None)

    def handle(self, node_id: str) -> MeshHandle | None:
        with self._lock:
            return self._handles.get(node_id)

    def snapshot(self) -> dict:
        """Placement map for /debug/vars: node id -> registration info."""
        with self._lock:
            return {
                nid: {"generation": h.generation}
                for nid, h in sorted(self._handles.items())
            }


_placement = MeshPlacement()


def default_placement() -> MeshPlacement:
    return _placement


def enabled() -> bool:
    """Mesh dispatch kill switch: ``PILOSA_MESH_DISPATCH=0`` forces every
    fan-out back onto the HTTP relay without touching any node config."""
    return os.environ.get("PILOSA_MESH_DISPATCH", "1").lower() not in (
        "0", "false", "no", "off",
    )
