"""Mesh-sharded fragment stacks and fused query kernels.

A ``ShardedField`` holds one field/view's fragments as a single
``uint32[n_shards, n_rows, W]`` tensor laid out over the mesh:

    bits: NamedSharding(mesh, P("shards", "rows", None))

Shard axis 0 is the reference's shard→node placement made static; row axis
1 is split tensor-parallel style. Queries are jitted once per shape:

* pair ops (Intersect/Union/Difference/Xor + Count): gather two rows —
  XLA all-gathers the row slice across the ``rows`` axis — then fused
  AND/popcount per shard and a psum-style reduce over the mesh.
* TopN: per-row popcounts reduced over (shards, words) — an ICI
  all-reduce — then ``lax.top_k`` replicated.
* BSI aggregates: plane-walk kernels from ops/bsi vmapped over shards.

The single-node executor (exec/executor.py) uses per-fragment dicts for
flexibility; this stacked path is the high-throughput lane used by the
benchmark and the distributed query planner.  The cluster layer reaches
the same stacked lane for PEER-owned shards too: a mesh-local partition
(cluster/dist.py + cluster/meshexec.py) folds in-process owner nodes'
fragments into the executor's ``[S, R, W]`` stacks, so a distributed
query over mesh-resident shards is one of these launches, not an HTTP
fan-out.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu.core.field import Field
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.ops import bitops, kernels

_OPS = {
    "intersect": lambda a, b: a & b,
    "union": lambda a, b: a | b,
    "difference": lambda a, b: a & ~b,
    "xor": lambda a, b: a ^ b,
}


@partial(jax.jit, static_argnames=("op",))  # graftlint: disable=launch-discipline -- legacy sharded facade; serving paths route via kernels funnels, direct users own their own accounting
def pair_op_count(bits, ra: jax.Array, rb: jax.Array, *, op: str) -> jax.Array:
    """Per-shard counts of op(Row(ra), Row(rb)) -> int32[n_shards].

    Summed to a Python int host-side so totals beyond 2^31 stay exact."""
    a = bits[:, ra]  # [S, W]; all-gathered across the rows axis by XLA
    b = bits[:, rb]
    return jnp.sum(
        lax.population_count(_OPS[op](a, b)).astype(jnp.int32), axis=-1
    )


def pair_counts_batched(bits, ras, rbs, *, op: str = "intersect"):
    """Batch of Count(op(Row, Row)) in one launch: ``int32[B, S]``
    per-shard partials on a local mesh (sum in int64 host-side;
    cross-shard totals may pass 2^31), or replicated ``np.int64[B]``
    in-program psum totals on a process-spanning mesh (kernels.py r05).

    Dispatches to the Pallas streaming kernel (ops/kernels.py) with an XLA
    scan fallback — the serving-mode replacement for the reference's
    per-query mapReduce (executor.go:2454-2518)."""
    return kernels.pair_count_batched(bits, ras, rbs, op=op)


@partial(jax.jit, donate_argnums=0)  # graftlint: disable=launch-discipline -- legacy sharded facade; serving paths route via kernels funnels, direct users own their own accounting
def apply_updates(bits, set_mask, clear_mask):
    """One write step: OR in set bits, ANDNOT clear bits. Donated so the
    update is in-place in HBM (the op-log flush analogue,
    reference fragment.go:2284-2293)."""
    return (bits | set_mask) & ~clear_mask


@partial(jax.jit, static_argnames=("depth",))  # graftlint: disable=launch-discipline -- legacy sharded facade; serving paths route via kernels funnels, direct users own their own accounting
def bsi_sum_planes(planes, exists, sign, filter_words, *, depth: int):
    """Per-plane popcounts for Sum over a sharded BSI stack.

    planes: [S, depth, W]; exists/sign/filter: [S, W]. Returns
    (pos[depth], neg[depth], count) int32 — combined with place values on
    host for arbitrary precision."""
    f = exists & filter_words
    pos = f & ~sign
    neg = f & sign
    pos_counts = []
    neg_counts = []
    for k in range(depth):
        p = planes[:, k]
        pos_counts.append(jnp.sum(lax.population_count(p & pos).astype(jnp.int32)))
        neg_counts.append(jnp.sum(lax.population_count(p & neg).astype(jnp.int32)))
    count = jnp.sum(lax.population_count(f).astype(jnp.int32))
    return (
        jnp.stack(pos_counts) if depth else jnp.zeros((0,), jnp.int32),
        jnp.stack(neg_counts) if depth else jnp.zeros((0,), jnp.int32),
        count,
    )


class ShardedField:
    """A field/view's fragments stacked onto a device mesh."""

    def __init__(
        self,
        bits: np.ndarray | jax.Array,
        row_ids: list[int],
        shard_ids: list[int],
        mesh: Mesh | None = None,
    ):
        self.row_ids = list(row_ids)
        self.shard_ids = list(shard_ids)
        self._slot_of = {r: i for i, r in enumerate(self.row_ids)}
        self.mesh = mesh
        if mesh is not None:
            sharding = NamedSharding(mesh, P("shards", "rows", None))
            self.bits = jax.device_put(bits, sharding)
        else:
            self.bits = jnp.asarray(bits)

    # -- construction -------------------------------------------------------

    @classmethod
    def from_field(
        cls,
        field: Field,
        mesh: Mesh | None = None,
        view: str = VIEW_STANDARD,
        pad_shards_to: int | None = None,
        pad_rows_to: int | None = None,
    ) -> "ShardedField":
        """Stack a field's per-shard fragments into [S, R, W]. Rows are the
        union of row ids across shards; both axes pad to mesh-divisible
        sizes."""
        v = field.view(view)
        frags = dict(v.fragments) if v is not None else {}
        shard_ids = sorted(frags)
        row_ids = sorted({r for f in frags.values() for r in f.row_ids()})
        S = max(len(shard_ids), 1)
        R = max(len(row_ids), 1)
        if mesh is not None:
            s_ax = mesh.shape["shards"]
            r_ax = mesh.shape["rows"]
            S = -(-S // s_ax) * s_ax
            R = -(-R // r_ax) * r_ax
        if pad_shards_to:
            S = max(S, pad_shards_to)
        if pad_rows_to:
            R = max(R, pad_rows_to)
        bits = np.zeros((S, R, field.n_words), dtype=np.uint32)
        for si, shard in enumerate(shard_ids):
            frag = frags[shard]
            for ri, row in enumerate(row_ids):
                if frag.has_row(row):
                    bits[si, ri] = frag.row_words_host(row)
        return cls(bits, row_ids, shard_ids, mesh)

    # -- queries ------------------------------------------------------------

    def slot(self, row_id: int) -> int:
        s = self._slot_of.get(row_id)
        if s is None:
            raise KeyError(f"row {row_id} not present")
        return s

    def count_pair(self, row_a: int, row_b: int, op: str = "intersect") -> int:
        per_shard = pair_op_count(
            self.bits,
            jnp.asarray(self.slot(row_a), jnp.int32),
            jnp.asarray(self.slot(row_b), jnp.int32),
            op=op,
        )
        return int(np.asarray(per_shard).astype(np.int64).sum())

    def count_pairs(
        self, pairs: list[tuple[int, int]], op: str = "intersect"
    ) -> list[int]:
        """Answer a batch of Count(op(Row(a), Row(b))) in one device launch."""
        ras = jnp.asarray([self.slot(a) for a, _ in pairs], jnp.int32)
        rbs = jnp.asarray([self.slot(b) for _, b in pairs], jnp.int32)
        out = np.asarray(
            pair_counts_batched(self.bits, ras, rbs, op=op)
        ).astype(np.int64)
        if out.ndim > 1:  # local mesh: [B, S] partials
            out = out.sum(axis=1)
        return [int(c) for c in out]

    def topn(self, n: int) -> list[tuple[int, int]]:
        n = min(n, len(self.row_ids)) or 1
        counts, slots = kernels.topn_counts(self.bits, n)
        counts = np.asarray(counts)
        slots = np.asarray(slots)
        out = []
        for c, s in zip(counts.tolist(), slots.tolist()):
            if c > 0 and s < len(self.row_ids):
                out.append((self.row_ids[s], c))
        return out

    def apply_updates(self, set_mask, clear_mask) -> None:
        """Donating write step; masks must match self.bits sharding."""
        self.bits = apply_updates(self.bits, set_mask, clear_mask)
