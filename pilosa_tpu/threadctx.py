"""Context-propagating thread helpers — the blessed way to cross a
thread boundary.

The request path rides on ``contextvars``: the deadline budget
(pilosa_tpu/deadline.py), the query profile (obs/qprofile.py), and the
device-cost tenant binding (obs/devledger.py) all follow a request
through function calls *on the same thread* for free — and silently
vanish the moment work hops to another thread, because a fresh thread
starts with an empty context.  A fan-out that forgets to snapshot loses
its deadline (the hop can outlive the budget unbounded) and its tenant
(device cost lands on the default principal).

``cluster/dist.py`` already does this for its fan-out pool with an
explicit ``contextvars.copy_context()``; this module is the same idiom
packaged so one-off spawns don't re-derive it.  The graftlint
``thread-boundary`` pass flags any ``threading.Thread(target=...)`` or
``pool.submit(...)`` whose target transitively reads one of those
contextvars unless the spawn site snapshots context (this helper or a
literal ``copy_context``) or carries a reasoned suppression.

Deliberately *not* used for long-lived service threads (batcher
dispatcher, membership monitor, flight recorder, ...): those start at
boot where there is no request context to capture, and capturing one
would pin whatever context the constructor happened to run under.  Such
sites suppress the pass with the reason spelled out.
"""

from __future__ import annotations

import contextvars
import threading
from typing import Callable


def wrap(fn: Callable, *args, **kwargs) -> Callable[[], object]:
    """Snapshot the caller's context NOW; the returned thunk replays
    ``fn(*args, **kwargs)`` inside that snapshot on whatever thread runs
    it.  Use for executor ``submit``::

        pool.submit(threadctx.wrap(work, item))
    """
    ctx = contextvars.copy_context()

    def run():
        return ctx.run(fn, *args, **kwargs)

    return run


def spawn(
    target: Callable,
    *args,
    name: str | None = None,
    daemon: bool = True,
    **kwargs,
) -> threading.Thread:
    """``threading.Thread`` that runs ``target`` under a snapshot of the
    spawning thread's context (deadline, profile, tenant all ride
    along).  Daemonic by default: a context-carrying worker must never
    outlive the process that owned the request.  The thread is created
    started=False; callers ``.start()`` it (symmetry with bare Thread
    construction, and tests can inspect before running)."""
    return threading.Thread(
        target=wrap(target, *args, **kwargs), name=name, daemon=daemon
    )
