"""Query execution: PQL ASTs lowered to jitted XLA computations over
fragment tensors (the TPU replacement for reference executor.go)."""
