"""PQL executor (reference: executor.go, 3.2k LoC).

Recursive evaluation of the PQL AST over fragment tensors. Where the
reference runs per-shard map-reduce with goroutine pools and HTTP fan-out
(executor.go:2454-2611), this executor evaluates bitmap algebra directly on
device arrays — per-shard segments combined with fused XLA bitwise kernels
— and leaves multi-device fan-out to pilosa_tpu.parallel (shard_map over a
mesh) and multi-host fan-out to the cluster layer.

Dispatch mirrors the reference table (executor.go:277-342): Sum/Min/Max,
Clear/ClearRow/Store, Count, Set, SetRowAttrs/SetColumnAttrs, TopN, Rows,
GroupBy, Options, and the bitmap calls Row/Range/Difference/Intersect/
Union/Xor/Not/Shift (executor.go:653-680)."""

from __future__ import annotations

import itertools
import os
import threading
import time
import weakref
from datetime import datetime
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from pilosa_tpu import deadline, pql
from pilosa_tpu.core import membudget, residency, timequantum
from pilosa_tpu.obs import devledger, qprofile, tracing
from pilosa_tpu.core.field import (
    FIELD_TYPE_BOOL,
    FIELD_TYPE_INT,
    FALSE_ROW_ID,
    TRUE_ROW_ID,
    Field,
)
from pilosa_tpu.core.holder import Holder
from pilosa_tpu.core.index import Index
from pilosa_tpu.core.translate import TranslateStore
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec import planner as planner_mod
from pilosa_tpu.exec import rescache
from pilosa_tpu.exec.result import (
    FieldRow,
    GroupCount,
    Pair,
    Row,
    RowIdentifiers,
    ValCount,
)
from pilosa_tpu.ops import bitops, bsi
from pilosa_tpu.pql.ast import Call, Condition

# reference executor.go:66 defaultMinThreshold.
DEFAULT_MIN_THRESHOLD = 1

# Sentinel for "not yet computed" result slots in the batch fast path.
_UNSET = object()

# Device cost ledger site for executor-owned launches: stack uploads and
# the BSI predicate/aggregate dispatches that don't funnel through the
# kernels dispatch notes (those book under ops.kernels / ops.bsi).
_DL_STACK = devledger.site("executor.stack_launch")
# pair-count gram/scan answers: the per-item measured price the flight
# planner's lane chooser weighs against the host latency tier
# (exec/planner.py)
_DL_PAIR = devledger.site("executor.pair_counts")

# Largest stacked [S, R, W] tensor the batch fast path will materialize.
_STACK_BUDGET_BYTES = 4 << 30  # device serving stacks; tuned for v5e HBM

_PAIR_OPS = {
    "Intersect": "intersect",
    "Union": "union",
    "Difference": "difference",
    "Xor": "xor",
}

# Calls that mutate state; the batch fast path must not answer reads that
# appear after one of these in the same query (in-order semantics).
_WRITE_CALLS = {
    "Set",
    "Clear",
    "ClearRow",
    "Store",
    "SetRowAttrs",
    "SetColumnAttrs",
}


def _pow2(n: int) -> int:
    """Batch sizes pad to powers of two so jit programs are reused
    across drifting batch sizes (shared impl: ops/bitops)."""
    return bitops.pow2_pad_len(n)


def _is_write(call: Call) -> bool:
    """A call writes if it or any descendant writes — Options() (and any
    future wrapper) can wrap a write, so the barrier walks the tree."""
    if call.name in _WRITE_CALLS:
        return True
    return any(_is_write(c) for c in call.children)


class ExecuteError(Exception):
    pass


class TooManyWritesError(ExecuteError):
    """reference pilosa.go:59 ErrTooManyWrites."""


class IndexNotFoundError(ExecuteError):
    pass


class FieldNotFoundError(ExecuteError):
    pass


class Executor:
    # reference server/config.go:160 MaxWritesPerRequest default
    DEFAULT_MAX_WRITES_PER_REQUEST = 5000

    def __init__(
        self,
        holder: Holder,
        translator: TranslateStore | None = None,
        max_writes_per_request: int | None = None,
        rescache_entries: int = 512,
        rescache_promote_hits: int = 3,
        rescache_demote_deltas: int = 64,
        planner_enabled: bool = True,
    ):
        self.holder = holder
        self.translator = translator or TranslateStore()
        # flight-level query planner (exec/planner.py, docs/serving.md
        # "Flight planning"): cross-query CSE + cost-based reordering +
        # measured lane choice, applied per execute_batch shard group
        self.planner = planner_mod.FlightPlanner(
            self, enabled=planner_enabled
        )
        # semantic result cache (exec/rescache.py, docs/caching.md):
        # translated read calls keyed by canonical AST + fragment version
        # vector, probed ahead of the batch fast paths; 0 entries
        # disables it
        self.rescache = rescache.ResultCache(
            entries=rescache_entries,
            promote_hits=rescache_promote_hits,
            demote_deltas=rescache_demote_deltas,
            stats_fn=lambda: holder.stats,
        )
        # mutating-call cap per request (reference executor.go:55,138 +
        # config max-writes-per-request); 0 disables
        self.max_writes_per_request = (
            self.DEFAULT_MAX_WRITES_PER_REQUEST
            if max_writes_per_request is None
            else max_writes_per_request
        )
        # stack maintenance accounting (tested: incremental refresh must
        # replace full re-uploads on write-interleaved workloads)
        self.stack_rebuilds = 0
        self.stack_incremental = 0
        # stacked-BSI launches (tests assert O(1) dispatch per BSI query)
        self.bsi_stack_launches = 0
        # pair counts answered from the cached host gram (zero device
        # work — the serving mode for repeat sequential queries)
        self.gram_cache_hits = 0
        # TopN row-count vectors served from the per-snapshot host cache
        self.rowcount_cache_hits = 0
        # GroupBy combination matrices served from the cached cross gram
        self.crossgram_cache_hits = 0
        # unfiltered BSI Sum/Min/Max scalars served per snapshot
        self.bsi_agg_cache_hits = 0
        # flight items the batch lane handed back to the per-call path
        # (malformed predicate, per-item compute trouble): the slot is
        # re-executed — and its error re-raised — in the owning query's
        # demux scope, so this counts fallbacks, not lost queries
        self.bsi_batch_item_errors = 0

    # ------------------------------------------------------------------ API

    def execute(
        self,
        index_name: str,
        query: str | pql.Query,
        shards: list[int] | None = None,
    ) -> list[Any]:
        """reference executor.go:116 Execute: translate -> execute ->
        attach attrs -> translate results."""
        idx = self.holder.index(index_name)
        if idx is None:
            raise IndexNotFoundError(f"index not found: {index_name}")
        q = pql.parse(query) if isinstance(query, str) else query
        if (
            self.max_writes_per_request > 0
            and len(q.write_calls()) > self.max_writes_per_request
        ):
            # reference executor.go:138 + pilosa.go:59 ErrTooManyWrites
            raise TooManyWritesError("too many write commands")
        # span per query (reference executor.go:117 "Executor.Execute")
        with tracing.start_span("executor.Execute").set_tag("index", index_name):
            calls = [c.clone() for c in q.calls]
            for call in calls:
                self._translate_call(idx, call)
            results: list[Any] = [_UNSET] * len(calls)
            # Serving-mode fast paths: many Count(op(Row,Row)) calls in
            # one query collapse into a single gram launch, and arbitrary
            # Row/op/Not trees compile into one traced program per AST
            # shape (exec/astbatch.py).  Only calls BEFORE the first
            # write are eligible: they observe exactly the pre-loop
            # state they would see executing in order.
            first_write = next(
                (i for i, c in enumerate(calls) if _is_write(c)), len(calls)
            )
            # Semantic cache probe ahead of kernel dispatch: a repeated
            # read whose fragment version vector is unchanged skips the
            # batch passes entirely (exec/rescache.py).
            tokens: list[Any] = [None] * len(calls)
            for i, call in enumerate(calls[:first_write]):
                res, tokens[i] = self.rescache.lookup(idx, call, shards)
                if res is not rescache.MISS:
                    results[i] = res
            self._batch_pair_counts(idx, calls[:first_write], shards, results)
            self._batch_general(idx, calls[:first_write], shards, results)
            self._batch_bsi(idx, calls[:first_write], shards, results)
            for i, call in enumerate(calls):
                if results[i] is _UNSET:
                    with tracing.start_span(f"executor.execute{call.name}"):
                        results[i] = self._execute_call(idx, call, shards)
            for i, call in enumerate(calls[:first_write]):
                if tokens[i] is not None:
                    self.rescache.store(
                        tokens[i],
                        results[i],
                        recompute=self._maintained_recompute(idx, call, shards),
                    )
            return [
                self._translate_result(idx, c, r) for c, r in zip(q.calls, results)
            ]

    def execute_batch(
        self,
        index_name: str,
        queries: list[tuple[str | pql.Query, list[int] | None]],
    ) -> list[Any]:
        """Cross-request micro-batch entry point (the continuous-batching
        serving plane, server/batcher.py): execute several independent
        read-only queries as ONE pass through the batched fast paths, so
        concurrent HTTP requests share gram/AST-batch device launches
        instead of each paying its own host→device round trip.

        ``queries`` is ``[(query, shards), ...]``.  Returns one slot per
        query: the query's result list, or the exception it raised —
        per-query isolation, one malformed query must not fail the
        flight it shares a window with.  A query that turns out to
        carry writes falls back to the ordinary in-order :meth:`execute`
        path (the batcher filters writes out already; this is the
        defensive second fence).  Queries with differing shard
        restrictions batch within their shard group."""
        idx = self.holder.index(index_name)
        if idx is None:
            err = IndexNotFoundError(f"index not found: {index_name}")
            return [err for _ in queries]
        n = len(queries)
        out: list[Any] = [None] * n
        parsed: list[pql.Query | None] = [None] * n
        cloned: list[list[Call] | None] = [None] * n
        with tracing.start_span("executor.ExecuteBatch").set_tag(
            "index", index_name
        ).set_tag("queries", n):
            # Per-query translate, grouped by shard restriction so the
            # flat batch passes see one consistent shard list.
            groups: dict[tuple[int, ...] | None, list[int]] = {}
            for qi, (query, shards) in enumerate(queries):
                try:
                    q = pql.parse(query) if isinstance(query, str) else query
                    if q.write_calls():
                        out[qi] = self.execute(index_name, q, shards=shards)
                        continue
                    parsed[qi] = q
                    calls = [c.clone() for c in q.calls]
                    for call in calls:
                        self._translate_call(idx, call)
                    cloned[qi] = calls
                    key = tuple(sorted(shards)) if shards else None
                    groups.setdefault(key, []).append(qi)
                except Exception as e:
                    out[qi] = e
            for key, qis in groups.items():
                shards = list(key) if key is not None else None
                flat_calls = [c for qi in qis for c in cloned[qi]]
                flat_results: list[Any] = [_UNSET] * len(flat_calls)
                # cache probe before the flat batch passes: flight
                # members served here never ride the device launch
                flat_tokens: list[Any] = [None] * len(flat_calls)
                for fi, call in enumerate(flat_calls):
                    res, flat_tokens[fi] = self.rescache.lookup(
                        idx, call, shards
                    )
                    if res is not rescache.MISS:
                        flat_results[fi] = res
                # flight planning AFTER the cache probe (tokens and keys
                # are captured; grafts/reorders cannot shift identity)
                # and BEFORE the batch passes (grafted trees must fall
                # to host segment algebra, which is the sharing win)
                self.planner.plan_group(
                    idx, flat_calls, shards, flat_results, _UNSET
                )
                self._batch_pair_counts(idx, flat_calls, shards, flat_results)
                self._batch_general(idx, flat_calls, shards, flat_results)
                self._batch_bsi(idx, flat_calls, shards, flat_results)
                pos = 0
                for qi in qis:
                    calls = cloned[qi]
                    res = flat_results[pos:pos + len(calls)]
                    toks = flat_tokens[pos:pos + len(calls)]
                    pos += len(calls)
                    try:
                        for ci, call in enumerate(calls):
                            if res[ci] is _UNSET:
                                with tracing.start_span(
                                    f"executor.execute{call.name}"
                                ):
                                    res[ci] = self._execute_call(
                                        idx, call, shards
                                    )
                        for ci, call in enumerate(calls):
                            if toks[ci] is not None:
                                self.rescache.store(
                                    toks[ci],
                                    res[ci],
                                    recompute=self._maintained_recompute(
                                        idx, call, shards
                                    ),
                                )
                        out[qi] = [
                            self._translate_result(idx, c, r)
                            for c, r in zip(parsed[qi].calls, res)
                        ]
                    except Exception as e:
                        out[qi] = e
        return out

    def rescache_probe(
        self,
        index_name: str,
        q: pql.Query,
        shards: list[int] | None = None,
    ) -> list[Any] | None:
        """All-or-nothing semantic cache probe for a whole parsed query:
        the batcher calls this at submit time so a flight member whose
        every call hits demuxes instantly instead of riding the device
        launch (server/batcher.py).  Returns the translated result list,
        or None when any call misses (the query then takes the normal
        path — the probe counts no miss twice since lookup tokens are
        discarded)."""
        idx = self.holder.index(index_name)
        if idx is None or not q.calls or q.write_calls():
            return None
        try:
            results = []
            for orig in q.calls:
                call = orig.clone()
                self._translate_call(idx, call)
                res, _tok = self.rescache.lookup(idx, call, shards)
                if res is rescache.MISS:
                    return None
                results.append(res)
            return [
                self._translate_result(idx, c, r)
                for c, r in zip(q.calls, results)
            ]
        except Exception:
            return None

    def rescache_degraded(
        self,
        index_name: str,
        q: pql.Query,
        shards: list[int] | None = None,
    ) -> list[Any] | None:
        """Degraded-tier variant of :meth:`rescache_probe`: all-or-
        nothing over LAST-KNOWN cache entries with the version check
        waived (rescache.lookup_stale).  The QoS governor routes a
        pressure-staged tenant's TopN/GroupBy here (server/qos.py);
        the caller marks the response as degraded.  Returns None when
        any call has no last-known entry — the query then runs for
        real at its reduced weight."""
        idx = self.holder.index(index_name)
        if idx is None or not q.calls or q.write_calls():
            return None
        try:
            results = []
            for orig in q.calls:
                call = orig.clone()
                self._translate_call(idx, call)
                res = self.rescache.lookup_stale(idx, call, shards)
                if res is rescache.MISS:
                    return None
                results.append(res)
            return [
                self._translate_result(idx, c, r)
                for c, r in zip(q.calls, results)
            ]
        except Exception:
            return None

    def cached_execute_call(
        self, idx: Index, call: Call, shards: list[int] | None
    ) -> Any:
        """One translated call through the semantic cache — the
        distributed layer's per-owner partial path (cluster/dist.py):
        local and mesh-facade partials cache under the owner's version
        subvector, so a reduce over partials stays correct across
        resize epochs (fragment epoch is part of the vector)."""
        res, token = self.rescache.lookup(idx, call, shards)
        if res is not rescache.MISS:
            return res
        out = self._execute_call(idx, call, shards)
        if token is not None:
            self.rescache.store(
                token, out,
                recompute=self._maintained_recompute(idx, call, shards),
            )
        return out

    def _maintained_recompute(
        self, idx: Index, call: Call, shards: list[int] | None
    ):
        """The promotion closure for hot TopN/GroupBy entries: re-derive
        the result from the incrementally maintained per-fragment row
        counts (``Fragment._counts``, carried through point writes and
        imports in the same group-commit) instead of invalidating.
        Unfiltered TopN re-merges the maintained counts host-side — no
        device dispatch; GroupBy re-runs its aggregation over the same
        maintained state.  Other call shapes don't promote (None)."""
        if call.name == "TopN" and not call.children:
            pass
        elif call.name == "GroupBy" and "filter" not in call.args:
            pass
        else:
            return None
        frozen = call.clone()

        def recompute():
            return self._execute_call(idx, frozen.clone(), shards)

        return recompute

    def _after_write(self, idx: Index, call: Call, result: Any) -> Any:
        self._note_write_call(idx, call)
        return result

    def _note_write_call(self, idx: Index, call: Call) -> None:
        """Eager precise invalidation after a write call executed: drop
        only the cache entries reading the written field.  Column-attr
        writes have no field — they drop the index's entries (attrs are
        outside the fragment version space, so the version vector can't
        catch them)."""
        name = call.name
        if name == "SetColumnAttrs":
            self.rescache.note_write(idx.name, None)
            return
        if name == "SetRowAttrs":
            fname = call.args.get("_field")
        else:
            fname = call.field_arg()
        if isinstance(fname, str):
            self.rescache.note_write(idx.name, fname)
            if idx.track_existence and name in ("Set", "Store"):
                self.rescache.note_write(idx.name, "_exists")
        else:
            self.rescache.note_write(idx.name, None)

    # ----------------------------------------------- batched Count fast path

    def _match_pair_count(self, idx: Index, call: Call):
        """(field_name, op, row_a, row_b) when ``call`` is a batchable
        ``Count(op(Row(f=a), Row(f=b)))`` over one set-like field; None
        otherwise."""
        if call.name != "Count" or len(call.children) != 1 or call.args:
            return None
        child = call.children[0]
        op = _PAIR_OPS.get(child.name)
        if op is None or len(child.children) != 2 or child.args:
            return None
        fname = None
        rows: list[int] = []
        for rc in child.children:
            if rc.name != "Row" or rc.children:
                return None
            f = rc.field_arg()
            if f is None or set(rc.args) != {f}:
                return None
            v = rc.args.get(f)
            if not isinstance(v, int) or isinstance(v, bool):
                return None
            if fname is None:
                fname = f
            elif fname != f:
                return None
            rows.append(v)
        field = idx.field(fname)
        if field is None or field.field_type == FIELD_TYPE_INT:
            return None
        if field.view(VIEW_STANDARD) is None:
            return None
        return fname, op, rows[0], rows[1]

    # stacks kept per (mesh, shard set); two entries so alternating shard
    # arguments don't evict each other every call
    _STACK_CACHE_ENTRIES = 2
    # monotonic use stamps for LRU eviction (shared across executors —
    # stamps only compare within one field's cache dict)
    _stack_lru_clock = itertools.count()

    def _field_stack(
        self,
        field: Field,
        shards: list[int],
        view_name: str = VIEW_STANDARD,
        fixed_rows: range | None = None,
    ):
        """(slot_of, bits[S, R, W] device tensor) for one of the field's
        views, DENSE over ``shards`` (all-zero slices where a shard has no
        fragment, so stacks of different fields share the shard axis —
        the GroupBy cross-field kernel needs that alignment). With more
        than one device visible the stack is laid out over the serving
        mesh — NamedSharding(mesh, P("shards")) with the shard axis
        padded to the mesh size — so every batched kernel runs on all
        chips (the reference's shard→node mapReduce, executor.go:2454,
        as a static placement).

        ``fixed_rows`` pins the row axis to position-aligned slots (the
        BSI layout: exists/sign/planes at rows 0..depth+1, reference
        fragment.go:90-96) instead of the union of observed row ids.

        Maintenance is INCREMENTAL: when cached fragment versions drift
        but the row set is unchanged, only the changed shards' row blocks
        are scattered into the device stack (one launch) instead of
        re-uploading the whole field — the write-batch analogue of the
        reference applying ops to an mmap'd fragment in place
        (fragment.go:2284-2293). None when over budget or empty."""
        from jax.sharding import NamedSharding, PartitionSpec
        from pilosa_tpu.parallel.mesh import serving_mesh

        v = field.view(view_name)
        if v is None:
            return None
        frags = {s: v.fragments[s] for s in shards if s in v.fragments}
        if not frags:
            return None
        mesh = serving_mesh()
        # key and layout must use the SAME resolved mesh: resolving twice
        # would let a concurrent configure_serving cache an old-mesh
        # layout under the new mesh's key
        cache_key = self._stack_key(
            shards, view_name,
            len(fixed_rows) if fixed_rows is not None else None,
            mesh=mesh,
        )
        versions = tuple(
            # (epoch, version): a re-created fragment (resize drop +
            # re-own) restarts version at 0, so the number alone could
            # alias a cached stack; the epoch pins the object identity
            (frags[s].epoch, frags[s].version) if s in frags else (-1, -1)
            for s in shards
        )
        budget = membudget.default_budget()
        # Per-FIELD lock (fields are shared between executors wrapping the
        # same holder); setdefault on the instance dict is atomic.
        lock = vars(field).setdefault("_stack_lock", threading.RLock())
        with lock:
            caches = vars(field).setdefault("_stack_caches", {})
            entry = caches.get(cache_key)
            if entry is not None:
                # LRU: stamp the entry on every hit; eviction below drops
                # the min-stamp entry.  A stamp (vs dict pop/reinsert)
                # leaves the budget's lock-free _evict pop as the only
                # writer that removes keys, so no KeyError/resurrection
                # race between a hit and a concurrent eviction.  The
                # budget touch doubles as the clock reference bit — use
                # stamps, not insertion order, drive its eviction scan —
                # and a hot enough entry graduates to a budget pin so an
                # oversubscribed tail can't evict the zipfian head.
                entry["lru"] = next(self._stack_lru_clock)
                entry["hits"] = entry.get("hits", 0) + 1
                tracker = residency.default_tracker()
                prefetching = tracker.in_prefetch()
                if entry["versions"] == versions:
                    budget.touch(entry["bkey"])
                    if prefetching:
                        # the prefetch thread found it already resident:
                        # the query (or an earlier prefetch) beat it here
                        tracker.note_prefetch_wasted()
                    else:
                        tracker.note_stack_hit()
                        tracker.note_hit(entry.get("prefetched", False))
                        entry["prefetched"] = False
                        if not entry.get("pinned") and tracker.maybe_pin_stack(
                            budget, entry["bkey"], entry["hits"]
                        ):
                            entry["pinned"] = True
                    return entry["slot_of"], entry["dev"]
                updated = self._stack_incremental_update(
                    field, entry, frags, shards, versions
                )
                if updated is not None:
                    budget.touch(entry["bkey"])
                    if prefetching:
                        # a refresh shipped only the drifted shards; the
                        # NEXT query's hit still credits the prefetch
                        entry["prefetched"] = True
                        tracker.note_prefetch_upload(0)
                    else:
                        tracker.note_stack_hit()
                        tracker.note_hit(entry.get("prefetched", False))
                        entry["prefetched"] = False
                    return updated
                caches.pop(cache_key, None)
                budget.release(entry["bkey"])

            if fixed_rows is not None:
                row_ids = list(fixed_rows)
            else:
                row_ids = sorted(
                    {r for f in frags.values() for r in f.row_ids()}
                )
            if not row_ids:
                return None
            S, R, W = len(shards), len(row_ids), field.n_words
            if mesh is not None:
                n_dev = mesh.devices.size
                S = -(-S // n_dev) * n_dev  # pad so the mesh divides the axis
            nbytes = S * R * W * 4
            if nbytes > _STACK_BUDGET_BYTES or budget.would_decline(nbytes):
                # over HBM budget: callers fall back to per-fragment paths,
                # which page rows under the same budget (membudget)
                return None
            slot_of = {r: i for i, r in enumerate(row_ids)}
            bits = np.zeros((S, R, W), dtype=np.uint32)
            for si, s in enumerate(shards):
                f = frags.get(s)
                if f is None:
                    continue
                # bulk matrix copy, not one Python call per row
                ids, matrix = f.rows_matrix_host()
                src = [
                    k for k, r in enumerate(ids) if r in slot_of
                ]  # fixed_rows: ignore strays
                if src:
                    dst = [slot_of[ids[k]] for k in src]
                    bits[si, dst] = matrix[src]
            if mesh is not None:
                dev = jax.device_put(
                    bits,
                    NamedSharding(mesh, PartitionSpec("shards", None, None)),
                )
            else:
                dev = jnp.asarray(bits)
            self.stack_rebuilds += 1
            from pilosa_tpu.ops import kernels

            kernels.note_transfer(nbytes, "h2d", dl_site=_DL_STACK)
            qprofile.incr("stack_rebuilds")
            # a BSI depth autogrow (or a standard view's row-set change)
            # retires same-(mesh, shards, view) entries with a different
            # row-axis length — they can never be hit again and would
            # otherwise strand a full device stack under a dead key
            for stale in [
                k for k in caches
                if k[:3] == cache_key[:3] and k[3] != cache_key[3]
            ]:
                old = caches.pop(stale, None)
                if old is not None:
                    budget.release(old["bkey"])
            while len(caches) >= self._STACK_CACHE_ENTRIES:
                # the budget's _evict pops lock-free, so snapshot-scan and
                # pop with defaults; retry when a concurrent pop races us
                try:
                    lru_key = min(
                        caches, key=lambda k: caches.get(k, {}).get("lru", -1)
                    )
                except (RuntimeError, ValueError):
                    continue  # dict mutated mid-scan; re-check the bound
                old = caches.pop(lru_key, None)  # least recently used
                if old is not None:
                    budget.release(old["bkey"])
            # Each cache entry carries its OWN budget key (two stacks per
            # field may be live; one shared key would undercount) and is
            # released whenever the entry is dropped.
            bkey = object()
            weakref.finalize(field, budget.release, bkey)
            tracker = residency.default_tracker()
            prefetched = tracker.in_prefetch()
            if prefetched:
                # built off the dispatch path by the residency
                # prefetcher: the first query hit counts it useful
                tracker.note_prefetch_upload(nbytes)
            else:
                tracker.note_miss()
            entry = {
                "versions": versions,
                "slot_of": slot_of,
                "dev": dev,
                "bkey": bkey,
                "lru": next(self._stack_lru_clock),
                # use-stamp hit count feeds the pin policy: a stack this
                # hot is exempted from budget eviction (residency.py)
                "hits": 0,
                "pinned": False,
                "prefetched": prefetched,
            }
            caches[cache_key] = entry

            def _evict(fref=weakref.ref(field), ck=cache_key):
                f = fref()
                if f is not None:
                    # lock-free atomic pop: the evicting thread may hold a
                    # different field's stack lock (AB-BA risk); a reader
                    # holding a reference to the popped entry just keeps
                    # using its (still-valid) device array
                    getattr(f, "_stack_caches", {}).pop(ck, None)

            budget.admit(bkey, nbytes, _evict)
            return slot_of, dev

    # incremental refresh only pays when few shards changed; past this
    # fraction a single bulk re-upload wins
    _STACK_INCR_MAX_FRACTION = 0.5

    def _stack_incremental_update(
        self, field: Field, entry: dict, frags, shards: list[int], versions
    ):
        """Refresh changed shards of a cached stack in one device scatter;
        None when a full rebuild is needed (row set grew, or too many
        shards drifted)."""
        slot_of = entry["slot_of"]
        changed = [
            si for si, (a, b) in enumerate(zip(entry["versions"], versions))
            if a != b
        ]
        if not changed or len(changed) > max(
            1, int(len(shards) * self._STACK_INCR_MAX_FRACTION)
        ):
            return None
        R = len(slot_of)
        W = field.n_words
        blocks = np.zeros((len(changed), R, W), dtype=np.uint32)
        for k, si in enumerate(changed):
            f = frags.get(shards[si])
            if f is None:
                return None
            # ONE locked snapshot: checking membership via a separate
            # row_ids() call would race a concurrent ingest adding a row
            # between the check and the copy
            ids, matrix = f.rows_matrix_host()
            dst = [slot_of.get(r) for r in ids]
            if any(s is None for s in dst):
                return None  # new row: shape change, full rebuild
            if ids:
                blocks[k, dst] = matrix
        dev = entry["dev"].at[jnp.asarray(changed, jnp.int32)].set(
            jnp.asarray(blocks)
        )
        entry.pop("gram", None)  # cached gram matched the old snapshot
        entry.pop("gram_misses", None)  # reuse restarts per snapshot
        entry.pop("rowcounts", None)  # ditto the served counts vector
        entry.pop("crossgram", None)  # ditto the cross-field gram
        entry.pop("crossgram_misses", None)
        entry.pop("bsi_agg", None)  # ditto the BSI aggregate scalars
        entry["dev"] = dev  # dev before versions: a racing reader keyed on
        entry["versions"] = versions  # versions must never see the old dev
        self.stack_incremental += 1
        qprofile.incr("stack_incremental")
        return slot_of, dev

    def _count_stat(self, idx: Index, call_name: str = "Count") -> None:
        """query_total stat for a batch-answered call (the per-call path
        emits this in _execute_call; batch paths must match)."""
        self.holder.stats.count_with_tags(
            "query_total", 1, 1.0, (f"index:{idx.name}", f"call:{call_name}")
        )

    # Fields up to this many rows may get their FULL gram computed and
    # cached on the stack entry — the reference's ranked cache analogue
    # (cache.go): repeat Count(op(Row,Row)) batches against an unchanged
    # field then answer from host memory with zero device work.
    _GRAM_CACHE_MAX_ROWS = 1024
    # subset-gram computations against one stack snapshot before the full
    # gram pays for itself (write-interleaved workloads never invest)
    _GRAM_CACHE_MIN_REUSE = 2

    def _field_gram(self, field: Field, bits, uniq):
        """(gram, pos) answering pair counts for the slot subset ``uniq``:
        a full-row gram cached on the stack entry (identity positions) or
        a fresh subset gram (enumerated positions); (None, None) when the
        gram path declines entirely.

        The cached gram is keyed to the entry's CURRENT device snapshot
        (stored under the field's stack lock, which the incremental
        refresh also holds) — a gram computed from an outdated ``bits``
        is never installed, so cached answers always match the snapshot
        the query reads.  The full gram is only computed when the subset
        nearly covers the rows anyway or the snapshot has already served
        _GRAM_CACHE_MIN_REUSE subset batches (observed reuse)."""
        from pilosa_tpu.ops import kernels

        R = bits.shape[1]
        entry = self._stack_entry_for(field, bits)
        if entry is not None and R <= self._GRAM_CACHE_MAX_ROWS:
            cached = entry.get("gram")
            if cached is not None and cached[0] is bits:
                self.gram_cache_hits += 1
                qprofile.incr("gram_cache_hits")
                return cached[1], {s: s for s in uniq}
            # the gram outlives the device stack: a budget-evicted field
            # re-staged with UNCHANGED fragment versions reattaches its
            # previous full gram ([R, R] host-tier metadata, tiny) with
            # zero device work — under oversubscription the bytes churn,
            # the derived artifacts shouldn't (docs/residency.md)
            hostg = vars(field).get("_gram_host")
            if hostg is not None and hostg[0] == (entry.get("versions"), R):
                g = hostg[1]
                lock = vars(field).setdefault(
                    "_stack_lock", threading.RLock()
                )
                with lock:
                    if entry.get("dev") is bits:
                        entry["gram"] = (bits, g)
                self.gram_cache_hits += 1
                qprofile.incr("gram_cache_hits")
                return g, {s: s for s in uniq}
            if (
                2 * len(uniq) >= R
                or entry.get("gram_misses", 0) >= self._GRAM_CACHE_MIN_REUSE
            ):
                g = kernels.pair_gram(bits, list(range(R)))
                if g is not None:
                    lock = vars(field).setdefault(
                        "_stack_lock", threading.RLock()
                    )
                    with lock:
                        if entry.get("dev") is bits:  # snapshot current
                            entry["gram"] = (bits, g)
                            field._gram_host = (
                                (entry.get("versions"), R), g,
                            )
                    return g, {s: s for s in uniq}
            else:
                # under the stack lock: _refresh pops entries under the
                # same lock, so the increment can't land on a stale entry
                lock = vars(field).setdefault("_stack_lock", threading.RLock())
                with lock:
                    entry["gram_misses"] = entry.get("gram_misses", 0) + 1
        g = kernels.pair_gram(bits, uniq)
        if g is None:
            return None, None
        return g, {s: k for k, s in enumerate(uniq)}

    # lone Count(op(Row,Row)) queries against one field seen before the
    # stack+gram investment is judged worthwhile for singles (the warm-up
    # the reference's ranked cache pays on its first TopN, cache.go)
    _PAIR_SINGLE_WARM = 4

    def _pair_single_ready(self, field: Field, shard_list: list[int]) -> bool:
        """Whether a LONE pair-count should take the gram path. True when
        a serving stack is already live (answering from it beats the
        per-fragment path, and repeat singles then install + hit the
        cached host gram: zero device work per query) or when repeat
        singles against this field prove reuse.  Once the cost ledger
        has priced both lanes, the measured comparison replaces the
        warm-up counter (exec/planner.py lane choice)."""
        if self._stack_cached(field, shard_list):
            return True
        lock = vars(field).setdefault("_stack_lock", threading.RLock())
        with lock:
            n = vars(field).get("_pair_single_demand", 0) + 1
            field._pair_single_demand = n
        return self.planner.choose_lane(
            "pair_count", n >= self._PAIR_SINGLE_WARM
        )

    @staticmethod
    def _stack_entry_for(field: Field, bits):
        """The stack-cache entry whose device snapshot IS ``bits``, found
        by identity rather than by rebuilding _field_stack's cache key
        (which would silently go stale if the key shape ever changed);
        the cache holds at most a handful of entries. The budget's _evict
        pops the dict lock-free from arbitrary threads, so the scan
        retries on a mid-iteration mutation and degrades to a cache miss
        rather than failing the query."""
        caches = getattr(field, "_stack_caches", None)
        if not caches:
            return None
        for _ in range(3):
            try:
                return next(
                    (
                        e
                        for e in list(caches.values())
                        if e.get("dev") is bits
                    ),
                    None,
                )
            except RuntimeError:
                continue  # dict mutated mid-scan; retry then miss
        return None

    def _stack_row_counts(self, field: Field, bits) -> np.ndarray:
        """Per-slot row counts ``int64 [R]`` for a stack snapshot, cached
        on the owning cache entry (keyed to the snapshot like the gram) —
        repeat unfiltered TopN against an unchanged field is then served
        from host memory with zero device work, the reference's
        ranked-cache role (cache.go).  A cached full gram's diagonal is
        reused instead of launching the count kernel."""
        from pilosa_tpu.ops import kernels

        entry = self._stack_entry_for(field, bits)
        if entry is not None:
            cached = entry.get("rowcounts")
            if cached is not None and cached[0] is bits:
                self.rowcount_cache_hits += 1
                qprofile.incr("rowcount_cache_hits")
                return cached[1]
            gram = entry.get("gram")
            if gram is not None and gram[0] is bits:
                rc = np.diag(gram[1]).astype(np.int64)
            else:
                rc = np.asarray(kernels.row_counts(bits)).astype(np.int64)
            lock = vars(field).setdefault("_stack_lock", threading.RLock())
            with lock:
                if entry.get("dev") is bits:  # snapshot still current
                    entry["rowcounts"] = (bits, rc)
            return rc
        return np.asarray(kernels.row_counts(bits)).astype(np.int64)

    # live cross-gram slots kept per stack entry (one per partner field);
    # each full gram is <= 8 MiB host memory at _GRAM_CACHE_MAX_ROWS
    _CROSS_GRAM_SLOTS = 4

    def _cross_slot(self, field: Field, bits, partner: str):
        """The (own_bits, partner_weakref, gram) slot cached on
        ``field``'s stack entry for ``partner``, dropping it if stale.
        Also returns the owning entry (or None)."""
        entry = self._stack_entry_for(field, bits)
        if entry is None:
            return None, None
        slots = entry.get("crossgram")
        t = slots.get(partner) if slots else None
        if t is not None:
            lock = vars(field).setdefault("_stack_lock", threading.RLock())
            if not (t[0] is bits and t[1]() is not None):
                # our snapshot moved, or the partner's was retired/
                # evicted — drop the slot now rather than letting it
                # linger
                with lock:
                    slots.pop(partner, None)
                t = None
            else:
                # LRU: move the hit slot to the end so the eviction loop
                # (which pops from the front) removes the coldest partner
                with lock:
                    cur = slots.pop(partner, None)
                    if cur is not None:
                        slots[partner] = cur
        return entry, t

    def _cross_gram(
        self, f1: Field, bits1, f2: Field, bits2, sub1: list, sub2: list
    ):
        """Cross-field intersection counts ``int64 [len(sub1), len(sub2)]``
        for two stack snapshots, with the same invest-on-reuse caching as
        ``_field_gram``: once repeat 2-level GroupBys against unchanged
        fields prove reuse, the FULL cross gram is computed once and every
        later combination matrix is sliced from host memory with zero
        device work.  Slots live on the first field's stack entry, one per
        partner field (so alternating partners don't thrash), and hold the
        partner's snapshot only WEAKLY — a cached gram must never keep a
        retired or budget-evicted device stack alive.  None when the gram
        path declines."""
        from pilosa_tpu.ops import kernels

        R1, R2 = bits1.shape[1], bits2.shape[1]
        if (
            R1 <= self._GRAM_CACHE_MAX_ROWS
            and R2 <= self._GRAM_CACHE_MAX_ROWS
        ):
            entry, t = self._cross_slot(f1, bits1, f2.name)
            if t is not None and t[1]() is bits2:
                self.crossgram_cache_hits += 1
                qprofile.incr("crossgram_cache_hits")
                return t[2][np.ix_(sub1, sub2)]
            # the reversed field order may already hold this gram
            # transposed (GroupBy(f, g) then GroupBy(g, f))
            _, t2 = self._cross_slot(f2, bits2, f1.name)
            if t2 is not None and t2[1]() is bits1:
                self.crossgram_cache_hits += 1
                qprofile.incr("crossgram_cache_hits")
                return t2[2].T[np.ix_(sub1, sub2)]
            if entry is not None:
                misses = entry.setdefault("crossgram_misses", {})
                nearly_full = 2 * len(sub1) >= R1 and 2 * len(sub2) >= R2
                if (
                    nearly_full
                    or misses.get(f2.name, 0) >= self._GRAM_CACHE_MIN_REUSE
                ):
                    g = kernels.cross_pair_gram(
                        bits1, bits2, list(range(R1)), list(range(R2))
                    )
                    if g is not None:
                        lock = vars(f1).setdefault(
                            "_stack_lock", threading.RLock()
                        )
                        with lock:
                            if entry.get("dev") is bits1:  # still current
                                slots = entry.setdefault("crossgram", {})
                                # pop-then-insert so an overwrite lands
                                # at the end (freshest LRU position)
                                slots.pop(f2.name, None)
                                slots[f2.name] = (
                                    bits1,
                                    weakref.ref(bits2),
                                    g,
                                )
                                while len(slots) > self._CROSS_GRAM_SLOTS:
                                    k = next(iter(slots), None)
                                    if k is None:
                                        break
                                    slots.pop(k, None)
                        return g[np.ix_(sub1, sub2)]
                else:
                    # same-lock discipline as the install path above
                    lock = vars(f1).setdefault(
                        "_stack_lock", threading.RLock()
                    )
                    with lock:
                        misses[f2.name] = misses.get(f2.name, 0) + 1
        return kernels.cross_pair_gram(bits1, bits2, sub1, sub2)

    def _batch_pair_counts(
        self, idx: Index, calls: list[Call], shards: list[int] | None,
        results: list[Any],
    ) -> None:
        """Answer every batchable Count(op(Row,Row)) call in ``calls``
        (the caller has already truncated at the first write barrier)
        with one gram launch per field — the serving-mode shape where the
        reference would run one goroutine map-reduce per query
        (executor.go:2454-2518).

        A field engages only when >= 2 of its Counts batch (the stack
        build is full-field; version-keyed caching makes it pay off on
        read-heavy serving workloads, while write-interleaved workloads
        fall through to the per-call path)."""
        from pilosa_tpu.ops import kernels

        by_field: dict[str, list[tuple[int, str, int, int]]] = {}
        for i, call in enumerate(calls):
            m = self._match_pair_count(idx, call)
            if m is not None:
                fname, op, ra, rb = m
                by_field.setdefault(fname, []).append((i, op, ra, rb))
        shard_list = None
        _count_stat = lambda: self._count_stat(idx)

        for fname, items in by_field.items():
            field = idx.field(fname)
            if shard_list is None:
                shard_list = self._shards_for(idx, shards)
            if len(items) < 2 and not self._pair_single_ready(
                field, shard_list
            ):
                continue
            stack = self._field_stack(field, shard_list)
            if stack is None:
                if len(items) < 2:
                    # over-budget field: restart the warm-up so singles
                    # don't pay a declined build attempt on every query
                    # (same lock as _pair_single_ready's read-modify-write,
                    # or a concurrent increment could overwrite the reset)
                    lock = vars(field).setdefault(
                        "_stack_lock", threading.RLock()
                    )
                    with lock:
                        field._pair_single_demand = 0
                continue
            slot_of, bits = stack
            launch: list[tuple[int, str, int, int]] = []
            for i, op, ra, rb in items:
                sa, sb = slot_of.get(ra), slot_of.get(rb)
                if sa is None or sb is None:
                    # Intersect with an absent row is provably 0; other
                    # ops (union/difference/xor) need the present side's
                    # count, so they take the normal path.
                    if op == "intersect":
                        results[i] = 0
                        _count_stat()
                    continue
                launch.append((i, op, sa, sb))
            if not launch:
                continue
            # One gram launch answers ALL ops in the batch — each pair op
            # is a formula over gram entries (|a|b| = Gaa+Gbb-Gab, ...),
            # so mixed Intersect/Union/Difference/Xor Counts share one
            # index scan on the MXU (kernels.pair_gram).
            uniq = sorted({s for _, _, sa, sb in launch for s in (sa, sb)})
            with tracing.start_span("executor.batchPairCount").set_tag(
                "field", fname
            ).set_tag("n", len(launch)), _DL_PAIR.launch(
                sig=f"gram n{len(launch)}", n=len(launch)
            ):
                gram, pos = self._field_gram(field, bits, uniq)
                if gram is not None:
                    pa = np.array([pos[sa] for _, _, sa, _ in launch])
                    pb = np.array([pos[sb] for _, _, _, sb in launch])
                    for op in {op for _, op, _, _ in launch}:
                        sel = [j for j, it in enumerate(launch) if it[1] == op]
                        counts = kernels.pair_counts_from_gram(
                            gram, pa[sel], pb[sel], op
                        )
                        for c, j in zip(counts, sel):
                            results[launch[j][0]] = int(c)
                            _count_stat()
                    continue
                # gram declined (too many distinct rows): scan kernels,
                # one launch per op, padded to powers of two for program
                # reuse.  Local stacks return [B, S] per-shard partials
                # (summed host-side in int64 so totals past 2^31 stay
                # exact); process-spanning stacks return replicated
                # int64[B] in-program psum totals (kernels.py r05).
                if not kernels.row_counts_supported(bits):
                    # spanning mesh too large even for the chunked psum
                    # — leave these results unset so the per-call
                    # per-fragment path answers them
                    continue
                by_op: dict[str, list[tuple[int, int, int]]] = {}
                for i, op, sa, sb in launch:
                    by_op.setdefault(op, []).append((i, sa, sb))
                for op, olaunch in by_op.items():
                    B = _pow2(len(olaunch))
                    if B > len(olaunch):
                        kernels.note_pad(
                            "pair_count",
                            B * bits.shape[0] * 4,
                            len(olaunch) * bits.shape[0] * 4,
                        )
                    ras = np.zeros(B, dtype=np.int32)
                    rbs = np.zeros(B, dtype=np.int32)
                    for j, (_, sa, sb) in enumerate(olaunch):
                        ras[j], rbs[j] = sa, sb
                    partials = np.asarray(
                        kernels.pair_count_batched(
                            bits, jnp.asarray(ras), jnp.asarray(rbs), op=op
                        )
                    ).astype(np.int64)
                    counts = (
                        partials if partials.ndim == 1
                        else partials.sum(axis=1)
                    )
                    for j, (i, _, _) in enumerate(olaunch):
                        results[i] = int(counts[j])
                        _count_stat()

    # ------------------------------------------ general AST one-launch path

    _UNRESOLVED = object()  # serving_mesh() may itself be None

    @staticmethod
    def _stack_key(
        shards: list[int],
        view_name: str,
        n_fixed_rows: int | None,
        mesh=_UNRESOLVED,
    ) -> tuple:
        """Stack-cache key. The mesh is part of the key: a device-set/
        configure_serving change must invalidate stacks built with the
        old sharding. View + row-axis length too: the standard and BSI
        stacks of one field share the cache dict, and a BSI depth
        autogrow must build a fresh (wider) stack. Pass ``mesh`` when the
        caller has already resolved it (and uses it for layout) so key
        and layout can never disagree."""
        from pilosa_tpu.parallel.mesh import serving_mesh

        if mesh is Executor._UNRESOLVED:
            mesh = serving_mesh()
        return (mesh, tuple(shards), view_name, n_fixed_rows)

    def _stack_cached(
        self,
        field: Field,
        shard_list: list[int],
        view_name: str = VIEW_STANDARD,
        n_fixed_rows: int | None = None,
    ) -> bool:
        """Whether a serving stack for this (field, shards) is already
        live — a peek that never builds."""
        caches = getattr(field, "_stack_caches", None)
        if not caches:
            return False
        return self._stack_key(shard_list, view_name, n_fixed_rows) in caches

    def prefetch_stack(
        self,
        field: Field,
        shard_list: list[int],
        view_name: str = VIEW_STANDARD,
    ) -> None:
        """Build (or refresh) the field's serving stack off the dispatch
        path — the residency prefetcher's target (server/prefetch.py).
        Runs on the uploader thread inside the tracker's prefetch
        context, so _field_stack books the transfer as prefetch traffic
        rather than a query miss; a stack the budget declines is simply
        not built (the dispatch falls back exactly as before).

        The derived serving artifacts ride along: a re-staged stack's
        pair-count gram is recomputed here too (same cache + snapshot
        discipline as _field_gram), so an evicted-then-prefetched field
        serves its next flight from the host gram with zero device work
        instead of paying the gram launch inside the dispatch."""
        st = self._field_stack(field, shard_list, view_name)
        if st is None:
            return
        _, bits = st
        R = bits.shape[1]
        if R > self._GRAM_CACHE_MAX_ROWS:
            return
        entry = self._stack_entry_for(field, bits)
        if entry is None:
            return
        cached = entry.get("gram")
        if cached is not None and cached[0] is bits:
            return
        lock = vars(field).setdefault("_stack_lock", threading.RLock())
        hostg = vars(field).get("_gram_host")
        if hostg is not None and hostg[0] == (entry.get("versions"), R):
            # versions unchanged since the last full gram: reattach the
            # host copy instead of relaunching
            with lock:
                if entry.get("dev") is bits:
                    entry["gram"] = (bits, hostg[1])
            return
        from pilosa_tpu.ops import kernels

        g = kernels.pair_gram(bits, list(range(R)))
        if g is not None:
            with lock:
                if entry.get("dev") is bits:  # snapshot still current
                    entry["gram"] = (bits, g)
                    field._gram_host = ((entry.get("versions"), R), g)

    def _batch_general(
        self, idx: Index, calls: list[Call], shards: list[int] | None,
        results: list[Any],
    ) -> None:
        """Compile remaining batchable reads — any tree of
        Row/Intersect/Union/Difference/Xor/Not, under Count or as a
        bitmap result — into one traced launch per AST shape over the
        field stacks (SURVEY §7's "one XLA program per query shape";
        reference semantics executor.go:653-680).

        The caller truncates ``calls`` at the first write barrier.  A
        call engages only when every leaf field either already has a
        live stack or is demanded by >= 2 batchable calls in this query
        (stack builds are full-field uploads; they must amortize)."""
        from pilosa_tpu.exec import astbatch

        # launch groups key on (canonical sig, actual stack pairs): the
        # COMPILED program is shared across groups with the same shape
        # (astbatch.compiled caches on sig alone — a rolling time window
        # reuses one program), but each group launches with its own
        # stacks
        count_groups: dict[tuple, list[tuple[int, list]]] = {}
        bitmap_items: list[tuple[int, tuple, tuple, list]] = []
        demand: dict[tuple[str, str], int] = {}
        for i, call in enumerate(calls):
            if results[i] is not _UNSET:
                continue
            leaves: list[tuple[str, str, int]] = []
            pairs: list[tuple[str, str]] = []
            sig = astbatch.match_count(idx, call, leaves, pairs)
            if sig is not None:
                count_groups.setdefault((sig, tuple(pairs)), []).append(
                    (i, leaves)
                )
            elif call.name in ("Intersect", "Union", "Difference", "Xor", "Not"):
                leaves, pairs = [], []
                sig = astbatch.match_tree(idx, call, leaves, pairs)
                if sig is None:
                    continue
                bitmap_items.append((i, sig, tuple(pairs), leaves))
            else:
                continue
            for pair in pairs:
                demand[pair] = demand.get(pair, 0) + 1
        if not count_groups and not bitmap_items:
            return
        shard_list = self._shards_for(idx, shards)

        # (field, view) -> stack entry | None (declined) | _ABSENT (no
        # such view: an all-zero leaf, e.g. an empty period of a
        # time-range cover)
        _ABSENT = object()
        stacks_by_view: dict[tuple[str, str], Any] = {}

        def _stacks_for(pairs, allow_spanning):
            """(stacks tuple, slot_of per (field, view)) or None when any
            leaf declines (cold + under-demanded, or over budget).
            ``allow_spanning``: count programs reduce in-program on a
            process-spanning mesh (astbatch._compiled_spanning), but
            bitmap programs materialize [S, W] result words for
            host-side Row segments — not addressable across processes,
            so those decline."""
            out: list[Any] = []
            slot_maps = {}
            for pair in pairs:
                fname, vname = pair
                if pair not in stacks_by_view:
                    field = idx.field(fname)  # includes _exists
                    if field is None:
                        stacks_by_view[pair] = None
                    elif field.view(vname) is None:
                        stacks_by_view[pair] = _ABSENT
                    elif self._stack_cached(
                        field, shard_list, vname
                    ) or self.planner.choose_lane(
                        # live stack: serving from it is free.  Cold:
                        # the >= 2 demand heuristic stands until the
                        # ledger prices the batch-vs-solo lanes.
                        "tree_count", demand.get(pair, 0) >= 2
                    ):
                        stacks_by_view[pair] = self._field_stack(
                            field, shard_list, view_name=vname
                        )
                    else:
                        stacks_by_view[pair] = None
                entry = stacks_by_view[pair]
                if entry is None:
                    return None
                if entry is _ABSENT:
                    slot_maps[pair] = {}
                    out.append(None)  # placeholder filled below
                else:
                    slot_maps[pair] = entry[0]
                    out.append(entry[1])
            # absent views still need a stack-shaped input for their
            # argument position: reuse any real stack — every such
            # leaf's slot is -1, which masks the gather to zero words
            real = next((a for a in out if a is not None), None)
            if real is None:
                return None  # every leaf view absent
            from pilosa_tpu.ops import kernels

            if not allow_spanning and kernels.stack_spans_processes(real):
                return None
            return tuple(a if a is not None else real for a in out), slot_maps

        def _slots_of(leaves, slot_maps) -> np.ndarray:
            # absent rows -> slot -1 (masked to zero words in the leaf)
            return np.array(
                [slot_maps[(f, vn)].get(r, -1) for f, vn, r in leaves],
                np.int32,
            )

        for (sig, pairs), items in count_groups.items():
            st = _stacks_for(pairs, allow_spanning=True)
            if st is None:
                continue
            stacks, slot_maps = st
            # same availability contract as every spanning lane: when
            # even a single-shard psum slice could overflow int32,
            # DECLINE to the per-call path instead of letting
            # run_count_batch's ValueError reach the client
            from pilosa_tpu.ops import kernels as _kk

            if not _kk.row_counts_supported(stacks[0]):
                continue
            B = _pow2(len(items))
            slots = np.full((B, len(items[0][1])), -1, np.int32)
            for j, (_, leaves) in enumerate(items):
                slots[j] = _slots_of(leaves, slot_maps)
            with tracing.start_span("executor.batchCountTree").set_tag(
                "n", len(items)
            ):
                totals = astbatch.run_count_batch(sig, stacks, slots)
            for j, (i, _) in enumerate(items):
                results[i] = int(totals[j])
                self._count_stat(idx)

        for i, sig, pairs, leaves in bitmap_items:
            st = _stacks_for(pairs, allow_spanning=False)
            if st is None:
                continue
            stacks, slot_maps = st
            with tracing.start_span("executor.batchBitmapTree"):
                dev = astbatch.run_bitmap(
                    sig, stacks, _slots_of(leaves, slot_maps)
                )
            if getattr(dev, "sharding", None) is not None and len(
                getattr(dev.sharding, "device_set", ())
            ) > 1:
                # mesh-sharded result: one host pull, numpy segments
                # (device slices would pin segments to different chips
                # and later segment algebra would mix placements)
                dev = np.asarray(dev)
            segments = {
                s: dev[si] for si, s in enumerate(shard_list)
            }
            results[i] = Row(segments, n_words=idx.n_words)
            self._count_stat(idx, calls[i].name)

    # ------------------------------------------------------- key translation

    def _field_of_call(self, idx: Index, call: Call) -> Field | None:
        fname = call.args.get("_field") or call.field_arg()
        if fname is None:
            return None
        return idx.field(fname)

    def _translate_call(self, idx: Index, call: Call) -> None:
        """keys -> ids in place. Mirrors the reference's per-call-name arg
        dispatch (executor.go:2625-2712 translateCall): each call shape
        names which args hold column keys vs row keys."""
        name = call.name
        if name == "GroupBy":
            self._translate_groupby(idx, call)
            return
        if name in ("Set", "Clear", "Row", "Range", "SetColumnAttrs", "ClearRow"):
            col_key = "_col"
            field_name = call.field_arg()
            row_key = field_name
        elif name == "SetRowAttrs":
            col_key = None
            row_key = "_row"
            field_name = call.args.get("_field")
        elif name == "Rows":
            field_name = call.args.get("_field")
            row_key = "previous"
            col_key = "column"
        else:
            col_key = "col"
            field_name = call.args.get("field")
            row_key = "row"

        # Translate column key (reference executor.go:2648-2664).
        if col_key is not None:
            col = call.args.get(col_key)
            if idx.keys:
                if col is not None and not isinstance(col, str):
                    raise ExecuteError(
                        "column value must be a string when index 'keys' option enabled"
                    )
                if isinstance(col, str):
                    call.args[col_key] = self.translator.translate_key(
                        idx.name, "", col
                    )
            elif isinstance(col, str):
                raise ExecuteError(
                    "string 'col' value not allowed unless index 'keys' option enabled"
                )

        # Translate row key (reference executor.go:2666-2712).
        if field_name:
            field = idx.field(field_name)
            if field is not None and row_key is not None:
                v = call.args.get(row_key)
                if field.field_type == FIELD_TYPE_BOOL and isinstance(v, bool):
                    call.args[row_key] = TRUE_ROW_ID if v else FALSE_ROW_ID
                elif field.keys:
                    if v is not None and not isinstance(v, str):
                        raise ExecuteError(
                            "row value must be a string when field 'keys' option enabled"
                        )
                    if isinstance(v, str):
                        call.args[row_key] = self.translator.translate_key(
                            idx.name, field_name, v
                        )
                elif isinstance(v, str):
                    raise ExecuteError(
                        "string 'row' value not allowed unless field 'keys' option enabled"
                    )

        for child in call.children:
            self._translate_call(idx, child)
        filt = call.args.get("filter")
        if isinstance(filt, Call):
            self._translate_call(idx, filt)

    def _translate_groupby(self, idx: Index, call: Call) -> None:
        """The `previous` paging list holds one row key/id per child field
        (reference executor.go:2718-2748 translateGroupByCall)."""
        for child in call.children:
            self._translate_call(idx, child)
        filt = call.args.get("filter")
        if isinstance(filt, Call):
            self._translate_call(idx, filt)
        previous = call.args.get("previous")
        if previous is None:
            return
        if not isinstance(previous, list):
            raise ExecuteError("'previous' argument must be a list")
        if len(previous) != len(call.children):
            raise ExecuteError(
                "'previous' argument must have a value for each GroupBy field"
            )
        for i, (child, prev) in enumerate(zip(call.children, previous)):
            fname = child.args.get("_field")
            field = idx.field(fname) if fname else None
            if field is None:
                continue
            if field.field_type == FIELD_TYPE_BOOL and isinstance(prev, bool):
                previous[i] = TRUE_ROW_ID if prev else FALSE_ROW_ID
            elif isinstance(prev, str):
                if not field.keys:
                    raise ExecuteError(
                        f"prev value must be a uint64 for field {fname!r}"
                    )
                previous[i] = self.translator.translate_key(idx.name, fname, prev)

    def _translate_result(self, idx: Index, call: Call, result: Any) -> Any:
        """ids -> keys on results (reference executor.go:2783-2907)."""
        if isinstance(result, Row) and idx.keys:
            result.keys = self.translator.translate_ids(
                idx.name, "", [int(c) for c in result.columns()]
            )
        elif isinstance(result, list) and result and isinstance(result[0], Pair):
            field = self._field_of_call(idx, call)
            if field is not None and field.keys:
                keys = self.translator.translate_ids(
                    idx.name, field.name, [p.id for p in result]
                )
                for p, k in zip(result, keys):
                    p.key = k
        elif isinstance(result, Pair):
            field = self._field_of_call(idx, call)
            if field is not None and field.keys:
                result.key = self.translator.translate_id(
                    idx.name, field.name, result.id
                )
        elif isinstance(result, RowIdentifiers):
            field = self._field_of_call(idx, call)
            if field is not None and field.keys:
                result.keys = self.translator.translate_ids(
                    idx.name, field.name, result.rows
                )
        elif isinstance(result, list) and result and isinstance(result[0], GroupCount):
            for gc in result:
                for fr in gc.group:
                    field = idx.field(fr.field)
                    if field is not None and field.keys:
                        fr.row_key = self.translator.translate_id(
                            idx.name, fr.field, fr.row_id
                        )
        return result

    # ------------------------------------------------------------- dispatch

    def _shards_for(self, idx: Index, shards: list[int] | None) -> list[int]:
        if shards is not None:
            return sorted(shards)
        return sorted(idx.available_shards())

    def _execute_call(self, idx: Index, call: Call, shards: list[int] | None) -> Any:
        name = call.name
        # Stop before starting a shard scan the caller will never wait
        # for — the deadline contextvar follows forwarded sub-queries
        # here via the X-Pilosa-Deadline header (pilosa_tpu/deadline.py).
        deadline.check(f"executing {name} on {idx.name!r}")
        # Per-call-type query counts (reference executor.go:298-339).
        self.holder.stats.count_with_tags(
            "query_total", 1, 1.0, (f"index:{idx.name}", f"call:{name}")
        )
        if name == "Sum":
            return self._execute_sum(idx, call, shards)
        if name == "Min":
            return self._execute_min_max(idx, call, shards, maximal=False)
        if name == "Max":
            return self._execute_min_max(idx, call, shards, maximal=True)
        if name == "MinRow":
            return self._execute_min_max_row(idx, call, shards, maximal=False)
        if name == "MaxRow":
            return self._execute_min_max_row(idx, call, shards, maximal=True)
        if name == "Clear":
            return self._after_write(idx, call, self._execute_clear(idx, call))
        if name == "ClearRow":
            return self._after_write(
                idx, call, self._execute_clear_row(idx, call, shards)
            )
        if name == "Store":
            return self._after_write(
                idx, call, self._execute_store(idx, call, shards)
            )
        if name == "Count":
            return self._execute_count(idx, call, shards)
        if name == "Set":
            return self._after_write(idx, call, self._execute_set(idx, call))
        if name == "SetRowAttrs":
            return self._after_write(
                idx, call, self._execute_set_row_attrs(idx, call)
            )
        if name == "SetColumnAttrs":
            return self._after_write(
                idx, call, self._execute_set_column_attrs(idx, call)
            )
        if name == "TopN":
            return self._execute_topn(idx, call, shards)
        if name == "Rows":
            return self._execute_rows(idx, call, shards)
        if name == "GroupBy":
            return self._execute_groupby(idx, call, shards)
        if name == "Options":
            return self._execute_options(idx, call, shards)
        # bitmap calls
        return self._execute_bitmap_call(idx, call, shards)

    # --------------------------------------------------------- bitmap calls

    def _execute_bitmap_call(self, idx: Index, call: Call, shards: list[int] | None) -> Row:
        """reference executor.go:653-680 executeBitmapCallShard + attr
        attach (executor.go:235-275)."""
        row = self._bitmap_call(idx, call, self._shards_for(idx, shards))
        # attach row attrs for a plain Row(f=<id>) (reference
        # executor.go:244-263)
        if call.name in ("Row", "Range"):
            fname = call.field_arg()
            if fname is not None:
                v = call.args.get(fname)
                field = idx.field(fname)
                if field is not None and isinstance(v, int) and not isinstance(v, bool):
                    row.attrs = field.row_attrs.attrs(v)
        return row

    # ------------------------------------------------ batched BSI fast path

    # filter-tensor ceiling for the fused batched Sum ([S, Q, W] uint32
    # per launch; past this the per-query host lane answers instead)
    _BSI_SUM_FILTER_BUDGET_BYTES = 256 << 20

    @staticmethod
    def _bsi_stored_bounds(field: Field, cond: Condition):
        """A condition's bounds in stored space (value - base), encoded
        for the batched kernels (ops/bsi.py condition_bounds)."""
        op = cond.op
        if op == "!=" and cond.value is None:
            return bsi.condition_bounds(op, None)
        if op == "><" or "x" in op:
            lo, hi = cond.int_pair()
            return bsi.condition_bounds(
                op, (lo - field.base, hi - field.base)
            )
        return bsi.condition_bounds(op, int(cond.value) - field.base)

    @staticmethod
    def _sum_valcount(field: Field, tc) -> ValCount:
        total, count = tc
        if count == 0:
            return ValCount()
        return ValCount(value=total + count * field.base, count=count)

    def _batch_bsi(
        self, idx: Index, calls: list[Call], shards: list[int] | None,
        results: list[Any],
    ) -> None:
        """Answer every BSI call astbatch signs as batchable with shared
        slice-plane launches: flight-mates group by (field, depth,
        op-class), so Q concurrent range predicates cost ONE
        range_batch/range_count_batch dispatch and Q filtered Sums ONE
        fused popcount matmul (ops/bsi.py batched kernels).  Per-item
        trouble leaves the slot _UNSET for the per-call path, which
        re-raises inside the owning query's demux scope — one bad query
        never fails its flight-mates.

        A field engages when >= 2 of its calls batch or its BSI stack is
        already live (the pair-count warm-up economics); a lone cold
        predicate keeps the per-call host latency tier."""
        from pilosa_tpu.exec import astbatch

        by_field: dict[str, list[tuple[int, str, Any]]] = {}
        fields: dict[str, Field] = {}
        for i, call in enumerate(calls):
            if results[i] is not _UNSET:
                continue
            m = astbatch.match_bsi(idx, call)
            if m is None:
                continue
            op_class, field, cond = m
            by_field.setdefault(field.name, []).append((i, op_class, cond))
            fields[field.name] = field
        if not by_field:
            return

        shard_list: list[int] | None = None
        for fname, items in by_field.items():
            field = fields[fname]
            if shard_list is None:
                shard_list = self._shards_for(idx, shards)
            if len(items) < 2 and not self._bsi_stack_live(
                field, shard_list
            ):
                continue
            bits = self._bsi_stack(field, shard_list)
            if bits is None:
                continue  # over budget: per-fragment path answers
            groups: dict[str, list[tuple[int, Any]]] = {}
            for i, op_class, cond in items:
                groups.setdefault(op_class, []).append((i, cond))
            with tracing.start_span("executor.batchBSI").set_tag(
                "field", fname
            ).set_tag("n", len(items)):
                self._batch_bsi_field(
                    idx, field, bits, groups, shard_list, calls, results
                )

    def _batch_bsi_field(
        self, idx: Index, field: Field, bits, groups, shard_list,
        calls: list[Call], results: list[Any],
    ) -> None:
        """One field's grouped BSI launches against its live stack."""
        from pilosa_tpu.exec import astbatch
        from pilosa_tpu.ops import kernels

        if kernels.stack_spans_processes(bits):
            # per-shard result words/partials are not host-addressable
            # across processes; the per-call paths keep their own story
            return
        depth = field.bit_depth
        split: list = []

        def tensors():
            if not split:
                split.append(self._bsi_split(bits))
            return split[0]

        # -- range masks: Range/Row trees and GroupBy filters share ONE
        # [Q, S, W] mask launch
        mask_items = groups.get(astbatch.BSI_RANGE, []) + groups.get(
            astbatch.BSI_GROUPBY, []
        )
        if mask_items:
            try:
                queries = [
                    self._bsi_stored_bounds(field, cond)
                    for _, cond in mask_items
                ]
            except (ValueError, TypeError):
                queries = None
            if queries is not None:
                exists, sign, planes = tensors()
                self.bsi_stack_launches += 1
                with tracing.start_span("executor.bsiRangeBatch").set_tag(
                    "n", len(mask_items)
                ):
                    masks = bsi.range_batch(
                        planes, exists, sign, queries, depth=depth
                    )
                if getattr(masks, "sharding", None) is not None and len(
                    getattr(masks.sharding, "device_set", ())
                ) > 1:
                    masks = np.asarray(masks)  # one pull for the flight
                for qi, (i, _) in enumerate(mask_items):
                    row = Row(n_words=self.holder.n_words)
                    m = masks[qi]
                    for si, s in enumerate(shard_list):
                        row.segments[s] = m[si]
                    if calls[i].name == "GroupBy":
                        try:
                            results[i] = self._execute_groupby(
                                idx, calls[i], shard_list, filt_row=row
                            )
                        except Exception:
                            # per-call path re-raises per query
                            self.bsi_batch_item_errors += 1
                    else:
                        results[i] = row

        # -- range counts: agg-cache hits first, the rest share one
        # count launch (no [Q, S, W] materialization)
        count_items = groups.get(astbatch.BSI_RANGE_COUNT, [])
        if count_items:
            pending: list[tuple[int, Any]] = []
            puts: list = []
            for i, cond in count_items:
                keyed = self._range_count_key(idx, calls[i].children[0])
                cached, put = (
                    self._bsi_agg_cache(field, bits, keyed[1])
                    if keyed is not None
                    else (None, lambda v: None)
                )
                if cached is not None:
                    results[i] = cached
                    self._count_stat(idx)
                else:
                    pending.append((i, cond))
                    puts.append(put)
            if pending:
                try:
                    queries = [
                        self._bsi_stored_bounds(field, cond)
                        for _, cond in pending
                    ]
                except (ValueError, TypeError):
                    queries = None
                if queries is not None:
                    exists, sign, planes = tensors()
                    self.bsi_stack_launches += 1
                    with tracing.start_span(
                        "executor.bsiRangeCountBatch"
                    ).set_tag("n", len(pending)):
                        counts = bsi.range_count_batch(
                            planes, exists, sign, queries, depth=depth
                        )
                    for (i, _), put, n in zip(pending, puts, counts):
                        put(n)
                        results[i] = n
                        self._count_stat(idx)

        # -- Sum: unfiltered repeats collapse onto the cached stacked
        # aggregate; filtered Sums share one fused popcount matmul when
        # the int32 accumulator and the filter tensor stay in budget
        sum_items = groups.get(astbatch.BSI_SUM, [])
        if sum_items:
            self._batch_bsi_sums(
                idx, field, bits, sum_items, shard_list, calls, results
            )

        # -- Min/Max: one cached scalar per (field, kind); grouped here
        # so the flight amortizes the stack build and each item fails
        # alone (cache-served repeats are host dictionary hits)
        for op_class, maximal in (
            (astbatch.BSI_MIN, False), (astbatch.BSI_MAX, True),
        ):
            for i, _ in groups.get(op_class, []):
                try:
                    results[i] = self._execute_min_max(
                        idx, calls[i], shard_list, maximal
                    )
                except Exception:
                    # per-call path re-raises per query
                    self.bsi_batch_item_errors += 1

    def _batch_bsi_sums(
        self, idx: Index, field: Field, bits, sum_items, shard_list,
        calls: list[Call], results: list[Any],
    ) -> None:
        from pilosa_tpu.ops import kernels

        depth = field.bit_depth
        S_stack, W = int(bits.shape[0]), field.n_words
        unfiltered: list[int] = []
        filtered: list[tuple[int, Row]] = []
        for i, _ in sum_items:
            try:
                filt = self._sum_filter(idx, calls[i], shard_list)
            except Exception:
                # malformed: per-call path raises per query
                self.bsi_batch_item_errors += 1
                continue
            if filt is None:
                unfiltered.append(i)
            else:
                filtered.append((i, filt))
        if unfiltered:
            # every unfiltered Sum in the flight is the SAME scalar:
            # one cached stacked compute answers them all
            try:
                tc = self._bsi_agg_serve(
                    field, (bits, None, shard_list), "sum",
                    lambda p, e, s, fw: bsi.sum_host(
                        p, e, s, fw, depth=depth
                    ),
                )
                for i in unfiltered:
                    results[i] = self._sum_valcount(field, tc)
            except Exception:
                # per-call path re-raises per query
                self.bsi_batch_item_errors += 1
        if not filtered:
            return
        Q = len(filtered)
        P = _pow2(Q)
        if (
            Q < 2
            or not bsi.sum_batch_supported(S_stack, W)
            or S_stack * P * W * 4 > self._BSI_SUM_FILTER_BUDGET_BYTES
        ):
            return  # per-query host lane (existing sum path) answers
        sh = getattr(bits, "sharding", None)
        if sh is not None and len(getattr(sh, "device_set", ())) > 1:
            # the [S, Q, W] filter tensor has no mesh layout matching the
            # stack's; keep the fused path single-device for now
            return
        fw = np.zeros((S_stack, P, W), np.uint32)
        for qi, (_, filt) in enumerate(filtered):
            fw[:, qi, :] = self._row_to_shard_matrix(
                filt, shard_list, S_stack, W
            )
        if P > Q:
            kernels.note_pad(
                "bsi_sum_batch", S_stack * P * W * 4, S_stack * Q * W * 4
            )
        exists, sign, planes = self._bsi_split(bits)
        filters = jnp.asarray(fw)
        self.bsi_stack_launches += 1
        with tracing.start_span("executor.bsiSumBatch").set_tag("n", Q):
            pairs = bsi.sum_batch_host(
                planes, exists, sign, filters, depth=depth
            )
        for (i, _), tc in zip(filtered, pairs):
            results[i] = self._sum_valcount(field, tc)

    def _bitmap_call(self, idx: Index, call: Call, shards: list[int]) -> Row:
        name = call.name
        if name == planner_mod.SHARED:
            # flight-shared operand (exec/planner.py): the row was
            # materialized once for the whole flight; copy like a cache
            # hit so consumers can attach keys/attrs independently
            return rescache.copy_result(planner_mod.shared_row(call))
        if name in ("Row", "Range"):
            return self._execute_row(idx, call, shards)
        if name == "Difference":
            return self._combine(idx, call, shards, "difference")
        if name == "Intersect":
            return self._combine(idx, call, shards, "intersect")
        if name == "Union":
            return self._combine(idx, call, shards, "union")
        if name == "Xor":
            return self._combine(idx, call, shards, "xor")
        if name == "Not":
            return self._execute_not(idx, call, shards)
        if name == "Shift":
            return self._execute_shift(idx, call, shards)
        raise ExecuteError(f"unknown call: {name}")

    def _combine(self, idx: Index, call: Call, shards: list[int], op: str) -> Row:
        if op == "intersect" and not call.children:
            raise ExecuteError("empty Intersect query is currently not supported")
        if not call.children:
            return Row(n_words=idx.n_words)
        # children evaluate lazily so an Intersect whose running result
        # is provably empty (no populated segments — the planner sorts
        # sparse operands first, exec/planner.py) skips the remaining
        # subtrees entirely
        out = self._bitmap_call(idx, call.children[0], shards)
        for c in call.children[1:]:
            if op == "intersect" and not out.segments:
                break
            out = getattr(out, op)(self._bitmap_call(idx, c, shards))
        return out

    def _execute_not(self, idx: Index, call: Call, shards: list[int]) -> Row:
        """Not() via the _exists field (reference executor.go executeNot)."""
        if not idx.track_existence:
            raise ExecuteError(
                "Not() query requires existence tracking to be enabled"
            )
        if len(call.children) != 1:
            raise ExecuteError("Not() takes one argument")
        ef = idx.existence_field()
        exists = self._field_row(ef, 0, shards)
        child = self._bitmap_call(idx, call.children[0], shards)
        return exists.difference(child)

    def _execute_shift(self, idx: Index, call: Call, shards: list[int]) -> Row:
        if len(call.children) != 1:
            raise ExecuteError("Shift() takes one argument")
        n, ok = call.int_arg("n")
        child = self._bitmap_call(idx, call.children[0], shards)
        # default n=0: unchanged row (reference executor.go:1773)
        return child.shift(n if ok else 0)

    def _field_row(self, field: Field | None, row_id: int, shards: list[int], view: str = VIEW_STANDARD) -> Row:
        """Row segments from the HOST mirrors — the per-call path is the
        latency tier, and the authoritative host copy answers a lone
        read without a device upload or result round trip (the
        throughput tier — batched grams, stacks — lives in
        _batch_pair_counts/_batch_general).  Downstream Row algebra and
        counts dispatch per segment type (exec/result.py)."""
        from pilosa_tpu.ops import kernels

        kernels.record_host_op("field_row")
        out = Row(n_words=self.holder.n_words)
        if field is None:
            return out
        v = field.view(view)
        if v is None:
            return out
        for shard in shards:
            frag = v.fragment(shard)
            if frag is not None:
                out.segments[shard] = frag.row_words_host(row_id)
        return out

    def _execute_row(self, idx: Index, call: Call, shards: list[int]) -> Row:
        """reference executor.go:1444 executeRowShard: plain row, BSI
        condition, or time range."""
        fname = call.field_arg()
        if fname is None:
            raise ExecuteError(f"{call.name}() requires a field argument")
        field = idx.field(fname)
        if field is None:
            raise FieldNotFoundError(f"field not found: {fname}")
        v = call.args.get(fname)
        if isinstance(v, Condition):
            return self._execute_bsi_condition(idx, field, v, shards)
        if "from" in call.args or "to" in call.args:
            return self._execute_time_range(idx, field, call, shards)
        if not isinstance(v, int) or isinstance(v, bool):
            raise ExecuteError(f"{call.name}() row argument must be an integer")
        if field.is_bsi():
            raise ExecuteError(
                f"{call.name}() cannot read a plain row from int field {fname!r}"
            )
        return self._field_row(field, v, shards)

    def _view_cover(self, field: Field, from_arg, to_arg) -> list[str] | None:
        try:
            return timequantum.view_cover(field, from_arg, to_arg, VIEW_STANDARD)
        except ValueError as e:
            raise ExecuteError(str(e))

    def _execute_time_range(self, idx: Index, field: Field, call: Call, shards: list[int]) -> Row:
        """Union of the minimal time-view cover (reference
        executor.go:1515-1531 + time.go viewsByTimeRange)."""
        fname = field.name
        row_id = call.args.get(fname)
        views = self._view_cover(
            field, call.args.get("from"), call.args.get("to")
        )
        out = Row(n_words=idx.n_words)
        if views is None:
            return out
        for vname in views:
            out = out.union(self._field_row(field, row_id, shards, view=vname))
        return out

    def _execute_bsi_condition(self, idx: Index, field: Field, cond: Condition, shards: list[int]) -> Row:
        """BSI range predicate -> bit-plane kernels (reference
        executor.go:1536-1566 executeBSIGroupRangeShard +
        fragment.go:1271-1534)."""
        if not field.is_bsi():
            raise ExecuteError(
                f"range condition on non-int field {field.name!r}"
            )
        # ONE warm-up decision per condition (a != evaluates two
        # kernels; they must not double-count demand)
        ready = self._bsi_single_ready(field, shards)
        op = cond.op
        if op == "!=" and cond.value is None:
            # f != null -> not-null (reference frag.notNull)
            return self._bsi_rows(
                field, shards, lambda pl, ex, sg: ex, ready=ready
            )
        if op == "==" and cond.value is None:
            raise ExecuteError("Range(): <field> == null is not supported")
        depth = field.bit_depth
        base = field.base

        if op in ("<", "<=", ">", ">="):
            bound = int(cond.value) - base
            fn = bsi.range_lt if op in ("<", "<=") else bsi.range_gt
            allow_eq = op in ("<=", ">=")
            return self._bsi_rows(
                field,
                shards,
                lambda pl, ex, sg: fn(
                    pl, ex, sg, value=bound, depth=depth, allow_eq=allow_eq
                ),
                ready=ready,
            )
        if op in ("==", "!="):
            stored = int(cond.value) - base
            eq = self._bsi_rows(
                field,
                shards,
                lambda pl, ex, sg: bsi.range_eq(
                    pl, ex, sg, value_abs=abs(stored), negative=stored < 0, depth=depth
                ),
                ready=ready,
            )
            if op == "==":
                return eq
            notnull = self._bsi_rows(
                field, shards, lambda pl, ex, sg: ex, ready=ready
            )
            return notnull.difference(eq)
        if op == "><":
            lo, hi = cond.int_pair()
            return self._bsi_rows(
                field,
                shards,
                lambda pl, ex, sg: bsi.range_between(
                    pl, ex, sg, lo=lo - base, hi=hi - base, depth=depth
                ),
                ready=ready,
            )
        if op in ("<x<", "<=x<", "<x<=", "<=x<="):
            lo, hi = cond.int_pair()
            lo_op, hi_op = op.split("x")
            lo_incl = lo if lo_op == "<=" else lo + 1
            hi_incl = hi if hi_op == "<=" else hi - 1
            return self._bsi_rows(
                field,
                shards,
                lambda pl, ex, sg: bsi.range_between(
                    pl, ex, sg, lo=lo_incl - base, hi=hi_incl - base, depth=depth
                ),
                ready=ready,
            )
        raise ExecuteError(f"unsupported condition op: {op}")

    def _bsi_stack(self, field: Field, shards: list[int]):
        """The raw ``uint32[S, depth+2, W]`` stacked BSI tensor (rows:
        exists=0, sign=1, planes 2..) or None (no view / over budget) —
        split into views via ``_bsi_split`` only when actually
        computing, so a cache-served aggregate pays no device dispatch.
        The stack is the same budget-accounted, incrementally-refreshed,
        mesh-sharded cache as standard-view stacks, with the row axis
        pinned to the BSI layout (exists=0, sign=1, planes 2.., reference
        fragment.go:90-96) so every Range/Sum/Min/Max batches all shards
        into one launch (reference fragment.go:1271-1534 runs the same
        scan per fragment)."""
        depth = field.bit_depth
        stack = self._field_stack(
            field,
            shards,
            view_name=field.bsi_view_name(),
            fixed_rows=range(2 + depth),
        )
        if stack is None:
            return None
        _, bits = stack  # [S, depth+2, W]
        return bits

    @staticmethod
    def _bsi_split(bits):
        """(exists, sign, planes) slices of a raw BSI stack.  Each slice
        is a device dispatch, so callers split only when they actually
        compute — a cache-served aggregate never pays it."""
        return bits[:, 0], bits[:, 1], bits[:, 2:]

    @staticmethod
    def _host_cpu_device():
        """The in-process CPU device for latency-tier kernel runs (the
        CPU backend coexists with the accelerator backend), or None."""
        try:
            return jax.local_devices(backend="cpu")[0]
        except Exception:
            return None

    # lone BSI predicates seen before the stack investment is judged
    # worthwhile (the BSI twin of _PAIR_SINGLE_WARM; 0 = invest on the
    # first query, i.e. the pre-round-4 behavior)
    _BSI_SINGLE_WARM = 4

    def _bsi_stack_live(self, field: Field, shards: list[int]) -> bool:
        """Peek (never build): whether the field's BSI stack is cached
        for these shards — the ONE place spelling the BSI stack key
        shape, shared by the warm-up decision and the agg-cache gate."""
        return self._stack_cached(
            field, shards, field.bsi_view_name(), 2 + field.bit_depth
        )

    def _bsi_single_ready(self, field: Field, shards: list[int]) -> bool:
        """Whether a LONE BSI predicate should take the device stack
        path — mirror of _pair_single_ready's warm-up economics: a live
        stack serves immediately; otherwise repeat demand must justify
        the full-field device upload before a lone query pays it."""
        if self._BSI_SINGLE_WARM <= 0:
            return True
        if self._bsi_stack_live(field, shards):
            return True
        lock = vars(field).setdefault("_stack_lock", threading.RLock())
        with lock:
            n = vars(field).get("_bsi_single_demand", 0) + 1
            field._bsi_single_demand = n
        return n >= self._BSI_SINGLE_WARM

    def _bsi_rows(
        self, field: Field, shards: list[int], kernel,
        ready: bool | None = None,
    ) -> Row:
        """Evaluate a BSI predicate kernel over every shard.  The kernels
        are shape-polymorphic (ops/bsi.py), so the stacked path runs the
        SAME compiled scan over [S, depth, W] in one launch; without a
        stack (over budget) each fragment launches separately.

        Latency tier: a LONE COLD predicate (no live stack, warm-up not
        reached) runs the SAME kernel on the in-process CPU backend over
        the fragment host mirrors — one compile per shape, then pure
        host execution, no device upload (the BSI twin of the host
        pair-count tier)."""
        out = Row(n_words=self.holder.n_words)
        if ready is None:
            ready = self._bsi_single_ready(field, shards)
        cpu = self._host_cpu_device()
        if cpu is not None and not ready:
            view = field.view(field.bsi_view_name())
            if view is None:
                return out
            frags = [
                (s, view.fragment(s))
                for s in shards
                if view.fragment(s) is not None
            ]
            if not frags:
                return out
            depth = field.bit_depth
            # ONE preallocated stacked buffer filled in place: the cold
            # query costs exactly one field-sized host copy, not three
            W = field.n_words
            planes = np.zeros((len(frags), depth, W), dtype=np.uint32)
            exists = np.zeros((len(frags), W), dtype=np.uint32)
            sign = np.zeros((len(frags), W), dtype=np.uint32)
            for si, (_, f) in enumerate(frags):
                f.fill_bsi_tensors_host(
                    depth, planes[si], exists[si], sign[si]
                )
            with _DL_STACK.launch(
                sig=f"bsi_rows/host d{depth}"
            ), jax.default_device(cpu):
                mask = np.asarray(
                    kernel(
                        jnp.asarray(planes), jnp.asarray(exists),
                        jnp.asarray(sign),
                    )
                )
            for si, (s, _) in enumerate(frags):
                out.segments[s] = mask[si]
            return out
        st = self._bsi_stack(field, shards)
        if st is not None:
            exists, sign, planes = self._bsi_split(st)
            self.bsi_stack_launches += 1
            with _DL_STACK.launch(sig=f"bsi_rows/stack d{field.bit_depth}"):
                mask = kernel(planes, exists, sign)  # [S, W], one launch
            if getattr(mask, "sharding", None) is not None and len(
                getattr(mask.sharding, "device_set", ())
            ) > 1:
                mask = np.asarray(mask)  # one pull; avoid mixed placements
            for si, s in enumerate(shards):
                out.segments[s] = mask[si]
            return out
        view = field.view(field.bsi_view_name())
        if view is None:
            return out
        for shard in shards:
            frag = view.fragment(shard)
            if frag is None:
                continue
            planes, exists, sign = frag.bsi_tensors(field.bit_depth)
            with _DL_STACK.launch(sig=f"bsi_rows/frag d{field.bit_depth}"):
                out.segments[shard] = kernel(planes, exists, sign)
        return out

    # ------------------------------------------------------------ aggregates

    def _range_count_key(self, idx: Index, child: Call):
        """(field, cache key) when ``child`` is a pure BSI range
        predicate — the repeat-dashboard shape ``Count(Range(v < N))``
        whose answer is a per-snapshot scalar; None otherwise."""
        if child.name not in ("Row", "Range") or child.children:
            return None
        fname = child.field_arg()
        if fname is None or set(child.args) != {fname}:
            return None
        field = idx.field(fname)
        if field is None or not field.is_bsi():
            return None
        cond = child.args.get(fname)
        if not isinstance(cond, Condition):
            return None
        v = cond.value
        if isinstance(v, list):
            v = tuple(v)
        return field, f"rangecount:{cond.op}:{v!r}"

    def _execute_count(self, idx: Index, call: Call, shards: list[int] | None) -> int:
        if len(call.children) != 1:
            raise ExecuteError("Count() takes one argument")
        child = call.children[0]
        shard_list = self._shards_for(idx, shards)
        keyed = self._range_count_key(idx, child)
        if keyed is not None:
            field, key = keyed
            # peek, never build: a lone cold range count must not pay a
            # full-field device upload for the agg cache (the host BSI
            # tier below answers it; repeat demand builds the stack)
            ready = self._BSI_SINGLE_WARM <= 0 or self._bsi_stack_live(
                field, shard_list
            )
            bits = self._bsi_stack(field, shard_list) if ready else None
            if bits is not None:
                cached, put = self._bsi_agg_cache(field, bits, key)
                if cached is not None:
                    return cached
                n = self._bitmap_call(idx, child, shard_list).count()
                put(n)
                return n
        # Latency tier: a lone Count over a pair or single row — the
        # gram fast path has already declined (cold field / single
        # query), so answer from the host mirrors with the fused native
        # kernel, zero copies (reference executor.go:1792 Count through
        # roaring.go:568's word loop).
        m = self._match_pair_count(idx, call)
        if m is not None:
            fname, op, ra, rb = m
            view = idx.field(fname).view(VIEW_STANDARD)
            t0 = time.perf_counter()
            total = self._host_pair_count(view, ra, rb, op, shard_list)
            # host-lane price note: what the lane chooser weighs against
            # the ledger's measured gram cost (exec/planner.py)
            self.planner.note_host_lane(
                "pair_count", (time.perf_counter() - t0) * 1e3
            )
            return total
        n = self._match_single_row_count(idx, child)
        if n is not None:
            field, row_id = n
            view = field.view(VIEW_STANDARD)
            if view is not None:
                # popcount(a) == popcount(a & a): ride the same fused
                # batched path as pair counts
                return self._host_pair_count(
                    view, row_id, row_id, "intersect", shard_list
                )
            return 0
        if child.name in (
            "Intersect", "Union", "Difference", "Xor", "Not"
        ) and not planner_mod.contains_shared(child):
            # solo host evaluation of a full tree: the batch-vs-solo
            # host-lane price (post-CSE combines are excluded — a
            # grafted tree is not a solo-evaluation sample)
            t0 = time.perf_counter()
            total = self._bitmap_call(idx, child, shard_list).count()
            self.planner.note_host_lane(
                "tree_count", (time.perf_counter() - t0) * 1e3
            )
            return total
        return self._bitmap_call(idx, child, shard_list).count()

    @staticmethod
    def _match_single_row_count(idx: Index, child: Call):
        """(field, row_id) when ``child`` is a plain ``Row(f=<id>)`` over
        a set-like field's standard view; None otherwise."""
        if child.name != "Row" or child.children:
            return None
        fname = child.field_arg()
        if fname is None or set(child.args) != {fname}:
            return None
        v = child.args.get(fname)
        if not isinstance(v, int) or isinstance(v, bool):
            return None
        field = idx.field(fname)
        if field is None or field.field_type == FIELD_TYPE_INT:
            return None
        return field, v

    # shards per latency-tier fan-out chunk; also the engage threshold —
    # below it the per-thread handoff costs more than it saves
    _HOST_FANOUT_CHUNK = 24

    def _host_pair_count(self, view, ra: int, rb: int, op: str, shard_list: list[int]) -> int:
        """Sum of fused host pair counts across shards, batched into ONE
        native call per chunk (per-shard ctypes crossings would cost
        more than the count itself at 100+ shards) and fanned across a
        small thread pool when the host has cores to use (the native
        kernel releases the GIL, so shard chunks count in parallel —
        the worker-pool role of reference executor.go:2557-2611)."""
        if view is None:
            return 0
        from pilosa_tpu.ops import kernels

        kernels.record_host_op("host_pair_count")
        frags = [
            f for f in (view.fragment(s) for s in shard_list) if f is not None
        ]
        if not frags:
            return 0
        cores = os.cpu_count() or 1
        if cores > 1 and len(frags) >= 2 * self._HOST_FANOUT_CHUNK:
            chunks = [
                frags[i : i + self._HOST_FANOUT_CHUNK]
                for i in range(0, len(frags), self._HOST_FANOUT_CHUNK)
            ]
            pool = self._host_tier_pool()
            return sum(
                pool.map(
                    lambda ch: self._host_pair_count_chunk(ch, ra, rb, op),
                    chunks,
                )
            )
        return self._host_pair_count_chunk(frags, ra, rb, op)

    @staticmethod
    def _host_pair_count_chunk(frags, ra: int, rb: int, op: str) -> int:
        """One fused native crossing for a chunk of fragments, with every
        fragment's lock held through the call so counts read a
        consistent snapshot (absent rows ride a shared zeros row, which
        yields the zero-row semantics of every op).  Row addresses are
        computed vectorized (base + slot*stride) so the whole fan costs
        one ctypes call and zero per-row marshalling.  Falls back to the
        per-fragment path when the native library is absent."""
        import contextlib

        from pilosa_tpu.ops import _hostops

        if _hostops.load() is None:
            return sum(f.row_pair_count(ra, rb, op) for f in frags)
        n = len(frags)
        n_words = frags[0].n_words
        zeros = np.zeros(n_words, dtype=np.uint32)
        zaddr = zeros.__array_interface__["data"][0]
        bases = np.empty(n, dtype=np.uint64)
        slots_a = np.empty(n, dtype=np.int64)
        slots_b = np.empty(n, dtype=np.int64)
        hosts = []  # keep every backing array alive through the call
        with contextlib.ExitStack() as st:
            for i, f in enumerate(frags):
                st.enter_context(f._lock)
                hosts.append(f._host)  # keep alive through the call
                bases[i] = f._host_addr  # maintained at _host reassignment
                sa = f._slot_of.get(ra)
                sb = f._slot_of.get(rb)
                slots_a[i] = -1 if sa is None else sa
                slots_b[i] = -1 if sb is None else sb
            stride = np.uint64(n_words * 4)
            addr_a = np.where(
                slots_a < 0, np.uint64(zaddr),
                bases + slots_a.astype(np.uint64) * stride,
            )
            addr_b = np.where(
                slots_b < 0, np.uint64(zaddr),
                bases + slots_b.astype(np.uint64) * stride,
            )
            total = _hostops.pair_count_addrs(addr_a, addr_b, n_words, op)
        if total is None:  # race: library vanished; serial fallback
            return sum(f.row_pair_count(ra, rb, op) for f in frags)
        return total

    # guards _host_pool creation: concurrent request threads must not
    # each build (and leak) a pool — same discipline as
    # DistributedExecutor._fanout_pool
    _host_pool_lock = threading.Lock()

    def _host_tier_pool(self):
        """Lazily built, executor-lifetime thread pool for latency-tier
        shard fan-out (never built on single-core hosts)."""
        pool = getattr(self, "_host_pool", None)
        if pool is None:
            import concurrent.futures

            with self._host_pool_lock:
                pool = getattr(self, "_host_pool", None)
                if pool is None:
                    pool = concurrent.futures.ThreadPoolExecutor(
                        max_workers=min(8, os.cpu_count() or 1),
                        thread_name_prefix="pilosa-hosttier",
                    )
                    self._host_pool = pool
        return pool

    def _sum_filter(self, idx: Index, call: Call, shards: list[int]):
        if len(call.children) > 1:
            raise ExecuteError(f"{call.name}() only accepts a single bitmap input")
        if call.children:
            return self._bitmap_call(idx, call.children[0], shards)
        return None

    def _bsi_field(self, idx: Index, call: Call) -> Field:
        fname, ok = call.string_arg("field")
        if not ok:
            fname = call.args.get("_field")
        if not fname:
            raise ExecuteError(f"{call.name}(): field required")
        field = idx.field(fname)
        if field is None:
            raise FieldNotFoundError(f"field not found: {fname}")
        return field

    def _bsi_agg_shards(self, idx: Index, call: Call, shards: list[int] | None):
        """Shared scaffold for Sum/Min/Max: resolve the BSI field and the
        optional filter child; returns (field, stacked_or_None,
        per_shard_generator).  The stacked form is a DEFERRED
        (raw_bits, filter_row, shards) triple — ``_bsi_tensors``
        materializes the (planes, exists, sign, filter-words) views on a
        cache miss, answering the aggregate in one launch; the generator
        is the per-fragment fallback when the stack declines (over
        budget)."""
        shards = self._shards_for(idx, shards)
        field = self._bsi_field(idx, call)
        filt = self._sum_filter(idx, call, shards)
        view = field.view(field.bsi_view_name())

        stacked = None
        bits = self._bsi_stack(field, shards)
        if bits is not None:
            # split + filter materialization deferred to _bsi_tensors:
            # a cache-served aggregate pays zero device dispatches
            stacked = (bits, filt, shards)

        def per_shard():
            if view is None:
                return
            ones = np.full(field.n_words, 0xFFFFFFFF, dtype=np.uint32)
            for shard in shards:
                frag = view.fragment(shard)
                if frag is None:
                    continue
                fw = ones
                if filt is not None:
                    fw = filt.segments.get(shard)
                    if fw is None:
                        continue
                planes, exists, sign = frag.bsi_tensors(field.bit_depth)
                yield planes, exists, sign, fw

        return field, stacked, per_shard()

    # scalar aggregates kept per BSI stack snapshot (sum + min/max +
    # repeat range-count bounds; each entry is a handful of ints)
    _BSI_AGG_SLOTS = 128

    def _bsi_agg_cache(self, field: Field, dev, key: str):
        """Per-snapshot cache of unfiltered BSI aggregate scalars on the
        BSI stack's cache entry (same identity-keyed, write-invalidated
        scheme as the gram/row-count serving caches): repeat unfiltered
        Sum/Min/Max against an unchanged field are host dictionary hits.
        Returns (cached tuple | None, setter)."""
        entry = self._stack_entry_for(field, dev)
        if entry is None:
            return None, lambda v: None
        slots = entry.get("bsi_agg")
        t = slots.get(key) if slots else None
        if t is not None and t[0] is dev:
            self.bsi_agg_cache_hits += 1
            qprofile.incr("bsi_agg_cache_hits")
            # LRU: move the hit key to the dict end so put()'s bounded
            # eviction (front-first) removes the coldest key, not a hot
            # one that happened to be inserted early
            lock = vars(field).setdefault("_stack_lock", threading.RLock())
            with lock:
                cur = slots.pop(key, None)
                if cur is not None:
                    slots[key] = cur
            return t[1], lambda v: None

        def put(v):
            lock = vars(field).setdefault("_stack_lock", threading.RLock())
            with lock:
                if entry.get("dev") is dev:  # snapshot still current
                    slots2 = entry.setdefault("bsi_agg", {})
                    slots2.pop(key, None)  # re-insert at the LRU end
                    slots2[key] = (dev, v)
                    # range-count keys are open-ended (one per distinct
                    # bound); bound the dict, oldest first
                    while len(slots2) > self._BSI_AGG_SLOTS:
                        k = next(iter(slots2), None)
                        if k is None:
                            break
                        slots2.pop(k, None)

        return None, put

    def _bsi_tensors(self, field: Field, stacked):
        """Materialize a deferred stacked tuple: split the raw stack and
        build the filter words (device dispatches — run only on a cache
        miss)."""
        bits, filt, shards = stacked
        exists, sign, planes = self._bsi_split(bits)
        if filt is None:
            # the kernels compute f = exists & filter, so exists
            # itself is the identity filter — no index-width upload
            fw = exists
        else:
            # the stack's shard axis is padded to the mesh size;
            # padded slices have exists == 0, so any filter value
            # there is inert
            S_stack = exists.shape[0]
            fw_np = np.zeros((S_stack, field.n_words), np.uint32)
            for si, s in enumerate(shards):
                seg = filt.segments.get(s)
                if seg is not None:
                    fw_np[si] = np.asarray(seg)
            sh = getattr(exists, "sharding", None)
            if sh is not None and len(getattr(sh, "device_set", ())) > 1:
                fw = jax.device_put(fw_np, sh)  # co-locate with stack
            else:
                fw = jnp.asarray(fw_np)
            _DL_STACK.record_transfer(fw_np.nbytes, "h2d")
        return planes, exists, sign, fw

    def _bsi_agg_serve(self, field: Field, stacked, key: str, compute):
        """Serve one stacked aggregate: per-snapshot cache hit for
        unfiltered queries, else materialize the tensors, run
        ``compute(planes, exists, sign, fw)``, and install (filtered
        queries always compute — their result depends on the filter)."""
        bits, filt, _ = stacked
        cached, put = (
            self._bsi_agg_cache(field, bits, key)
            if filt is None
            else (None, lambda v: None)
        )
        if cached is None:
            planes, exists, sign, fw = self._bsi_tensors(field, stacked)
            self.bsi_stack_launches += 1
            with _DL_STACK.launch(sig=f"bsi_agg/{key.split(':', 1)[0]}"):
                cached = compute(planes, exists, sign, fw)
            put(cached)
        return cached

    def _execute_sum(self, idx: Index, call: Call, shards: list[int] | None) -> ValCount:
        """reference executor.go:409-442 + executeSumCountShard."""
        field, stacked, tensors = self._bsi_agg_shards(idx, call, shards)
        if stacked is not None:
            total, count = self._bsi_agg_serve(
                field,
                stacked,
                "sum",
                lambda p, e, s, fw: bsi.sum_host(
                    p, e, s, fw, depth=field.bit_depth
                ),
            )
            if count == 0:
                return ValCount()
            return ValCount(value=total + count * field.base, count=count)
        total, count = 0, 0
        for planes, exists, sign, fw in tensors:
            s, c = bsi.sum_host(planes, exists, sign, fw, depth=field.bit_depth)
            total += s
            count += c
        if count == 0:
            return ValCount()
        return ValCount(value=total + count * field.base, count=count)

    def _execute_min_max(self, idx: Index, call: Call, shards: list[int] | None, maximal: bool) -> ValCount:
        field, stacked, tensors = self._bsi_agg_shards(idx, call, shards)
        if stacked is not None:
            # the stacked kernels reduce candidates globally across the
            # shard axis, which IS the per-shard merge (equal extremes
            # accumulate their counts)
            value, count = self._bsi_agg_serve(
                field,
                stacked,
                f"minmax:{maximal}",
                lambda p, e, s, fw: bsi.min_max_host(
                    p, e, s, fw, depth=field.bit_depth, maximal=maximal
                ),
            )
            if count == 0:
                return ValCount()
            return ValCount(value=value + field.base, count=count)
        best: ValCount | None = None
        for planes, exists, sign, fw in tensors:
            value, count = bsi.min_max_host(
                planes, exists, sign, fw, depth=field.bit_depth, maximal=maximal
            )
            if count == 0:
                continue
            value += field.base
            if best is None or (value > best.value if maximal else value < best.value):
                best = ValCount(value=value, count=count)
            elif value == best.value:
                best.count += count
        return best or ValCount()

    def _execute_min_max_row(self, idx: Index, call: Call, shards: list[int] | None, maximal: bool) -> Pair:
        """MinRow/MaxRow: extreme existing row id (reference
        executor.go:560-651)."""
        shards = self._shards_for(idx, shards)
        fname, ok = call.string_arg("field")
        if not ok:
            raise ExecuteError(f"{call.name}(): field required")
        field = idx.field(fname)
        if field is None:
            raise FieldNotFoundError(f"field not found: {fname}")
        view = field.view(VIEW_STANDARD)
        best: Pair | None = None
        if view is not None:
            for shard in shards:
                frag = view.fragment(shard)
                if frag is None:
                    continue
                ids, counts = frag.row_counts()
                # uint64: row ids span the full 64-bit space
                ids = np.asarray(ids, np.uint64)
                counts = np.asarray(counts, np.int64)
                nz = counts > 0  # vectorized extreme instead of a
                if not nz.any():  # per-row Python scan
                    continue
                rid = int(ids[nz].max() if maximal else ids[nz].min())
                cnt = int(counts[ids == rid][0])
                if best is None or (
                    rid > best.id if maximal else rid < best.id
                ):
                    best = Pair(id=rid, count=cnt)
                elif rid == best.id:
                    best.count += cnt
        return best or Pair()

    # ------------------------------------------------------------- mutations

    def _execute_set(self, idx: Index, call: Call) -> bool:
        """reference executor.go:2069 executeSet."""
        col, ok = call.uint_arg("_col")
        if not ok:
            raise ExecuteError("Set() column argument 'col' required")
        fname = call.field_arg()
        if fname is None:
            raise ExecuteError("Set() argument required: field")
        field = idx.field(fname)
        if field is None:
            raise FieldNotFoundError(f"field not found: {fname}")
        idx.add_column_existence(col)
        if field.is_bsi():
            value, ok = call.int_arg(fname)
            if not ok:
                raise ExecuteError("Set() row argument 'row' required")
            return field.set_value(col, value)
        row, ok = call.uint_arg(fname)
        if not ok:
            raise ExecuteError("Set() row argument 'row' required")
        ts = call.args.get("_timestamp")
        timestamp = timequantum.parse_time(ts) if ts is not None else None
        return field.set_bit(row, col, timestamp)

    def _execute_clear(self, idx: Index, call: Call) -> bool:
        col, ok = call.uint_arg("_col")
        if not ok:
            raise ExecuteError("Clear() column argument required")
        fname = call.field_arg()
        if fname is None:
            raise ExecuteError("Clear() argument required: field")
        field = idx.field(fname)
        if field is None:
            raise FieldNotFoundError(f"field not found: {fname}")
        if field.is_bsi():
            # reference semantics: Clear on an int field clears nothing via
            # the standard view; we clear the stored value when the arg
            # matches the column's current value is NOT checked (v1.3
            # behavior: ClearBit on bsi fields is a no-op through views).
            return field.clear_value(col)
        row, ok = call.uint_arg(fname)
        if not ok:
            raise ExecuteError("row=<row> argument required to Clear() call")
        return field.clear_bit(row, col)

    def _execute_clear_row(self, idx: Index, call: Call, shards: list[int] | None) -> bool:
        """reference executor.go:1899-1997."""
        fname = call.field_arg()
        if fname is None:
            raise ExecuteError("ClearRow() argument required: field")
        field = idx.field(fname)
        if field is None:
            raise FieldNotFoundError(f"field not found: {fname}")
        if field.field_type not in ("set", "time", "mutex", "bool"):
            raise ExecuteError(
                f"ClearRow() is not supported on {field.field_type} fields"
            )
        row = call.args.get(fname)
        if not isinstance(row, int) or isinstance(row, bool):
            raise ExecuteError("ClearRow() requires a row argument")
        changed = False
        v = field.view(VIEW_STANDARD)
        if v is not None:
            for shard in self._shards_for(idx, shards):
                frag = v.fragment(shard)
                if frag is not None:
                    changed |= frag.clear_row(row)
        return changed

    def _execute_store(self, idx: Index, call: Call, shards: list[int] | None) -> bool:
        """Store(child, f=row): write child bitmap as a row (reference
        executor.go:1999-2067 executeSetRow)."""
        if len(call.children) != 1:
            raise ExecuteError("Store() requires a source query")
        fname = call.field_arg()
        if fname is None:
            raise ExecuteError("Store() argument required: field")
        field = idx.field(fname)
        if field is None:
            # reference creates a set field on demand for Store
            # (executor.go:2016-2023).
            field = idx.create_field(fname)
        row = call.args.get(fname)
        if not isinstance(row, int) or isinstance(row, bool):
            raise ExecuteError("Store() requires a row argument")
        shards = self._shards_for(idx, shards)
        child = self._bitmap_call(idx, call.children[0], shards)
        view = field.create_view_if_not_exists(VIEW_STANDARD)
        changed = False
        for shard in shards:
            seg = child.segments.get(shard)
            words = (
                np.zeros(field.n_words, dtype=np.uint32)
                if seg is None
                else np.asarray(seg)
            )
            frag = view.create_fragment_if_not_exists(shard)
            changed |= frag.set_row_words(row, words)
        return changed

    def _execute_set_row_attrs(self, idx: Index, call: Call) -> None:
        fname, ok = call.string_arg("_field")
        field = idx.field(fname) if ok else None
        if field is None:
            raise FieldNotFoundError("SetRowAttrs() field not found")
        row, ok = call.uint_arg("_row")
        if not ok:
            raise ExecuteError("SetRowAttrs() row required")
        attrs = {
            k: v for k, v in call.args.items() if k not in ("_field", "_row")
        }
        field.row_attrs.set_attrs(row, attrs)
        return None

    def _execute_set_column_attrs(self, idx: Index, call: Call) -> None:
        col, ok = call.uint_arg("_col")
        if not ok:
            raise ExecuteError("SetColumnAttrs() column required")
        attrs = {k: v for k, v in call.args.items() if k != "_col"}
        idx.column_attrs.set_attrs(col, attrs)
        return None

    # ------------------------------------------------------------------ TopN

    def _execute_topn(self, idx: Index, call: Call, shards: list[int] | None) -> list[Pair]:
        """Exact TopN (reference executor.go:860-999 is two-phase because
        per-shard caches are approximate; device row counts are exact, so a
        single pass suffices and strictly dominates the reference's
        accuracy)."""
        shards = self._shards_for(idx, shards)
        fname, ok = call.string_arg("_field")
        if not ok:
            raise ExecuteError("TopN() field required")
        field = idx.field(fname)
        if field is None:
            raise FieldNotFoundError(f"field not found: {fname}")
        if field.is_bsi():
            raise ExecuteError(f"cannot compute TopN() on integer field: {fname!r}")
        if field.options.cache_type == "none":
            raise ExecuteError(f"cannot compute TopN(), field has no cache: {fname!r}")
        n, _ = call.uint_arg("n")
        ids_arg, has_ids = call.uint_slice_arg("ids")
        threshold, has_threshold = call.uint_arg("threshold")
        if not has_threshold or threshold == 0:
            threshold = DEFAULT_MIN_THRESHOLD
        tanimoto, has_tanimoto = call.uint_arg("tanimotoThreshold")
        if has_tanimoto and tanimoto > 100:
            raise ExecuteError("Tanimoto Threshold is from 1 to 100 only")
        attr_name, _ = call.string_arg("attrName")
        attr_values = call.args.get("attrValues")

        src: Row | None = None
        if len(call.children) == 1:
            src = self._bitmap_call(idx, call.children[0], shards)
        elif len(call.children) > 1:
            raise ExecuteError("TopN() can only have one input bitmap")

        view = field.view(VIEW_STANDARD)
        counts: dict[int, int] = {}
        src_count = src.count() if src is not None else 0
        row_totals: dict[int, int] = {}
        # Two-tier dispatch: UNFILTERED TopN is served from the
        # MAINTAINED per-fragment counts (host, no device work, stays
        # correct across writes via the import/point-write delta
        # carrying — the reference's ranked cache, cache.go:158); the
        # stack path is the throughput tier for FILTERED TopN where a
        # masked-count kernel earns its launch.
        if view is not None and src is not None:
            # One launch over the cached field stack answers every
            # (shard, row) at once via the masked-count kernel (replacing
            # the reference's per-fragment cache merge and the per-shard
            # filter loop, fragment.go:1586-1655).
            from pilosa_tpu.ops import kernels

            stack = self._field_stack(field, shards)
            if stack is not None:
                # masked counts run in-program (psum) on
                # process-spanning stacks too; the only decline left is
                # totals past even a single-shard psum slice's int32
                # bound — the per-fragment loop below answers then
                if not kernels.row_counts_supported(stack[1]):
                    stack = None
            if stack is not None:
                slot_of, bits = stack
                S, _, W = bits.shape
                filt = self._row_to_shard_matrix(src, shards, S, W)
                mc = kernels.masked_row_counts(bits, filt)
                for rid, slot in slot_of.items():
                    if mc[slot]:
                        counts[rid] = int(mc[slot])
                if has_tanimoto:
                    rc = self._stack_row_counts(field, bits)
                    for rid, slot in slot_of.items():
                        if rc[slot]:
                            row_totals[rid] = int(rc[slot])
                view = None  # stack covered every shard; skip the loop
        if view is not None and src is None:
            # vectorized merge of the maintained per-fragment counts:
            # concatenate (ids, counts) across shards and reduce by row
            # id — no per-(shard, row) Python work
            id_parts: list[np.ndarray] = []
            count_parts: list[np.ndarray] = []
            for shard in shards:
                frag = view.fragment(shard)
                if frag is None:
                    continue
                ids, row_counts = frag.row_counts()
                if ids:
                    id_parts.append(np.asarray(ids, dtype=np.int64))
                    count_parts.append(row_counts)
            if id_parts:
                cat_ids = np.concatenate(id_parts)
                cat_counts = np.concatenate(count_parts)
                uids, inv = np.unique(cat_ids, return_inverse=True)
                sums = np.bincount(
                    inv, weights=cat_counts, minlength=len(uids)
                ).astype(np.int64)
                nz = sums > 0
                counts = {
                    int(r): int(c)
                    for r, c in zip(uids[nz], sums[nz])
                }
            view = None  # merged every shard; skip the loop below
        if view is not None:
            for shard in shards:
                frag = view.fragment(shard)
                if frag is None:
                    continue
                # this loop only runs FILTERED (src set): the unfiltered
                # case merged maintained counts above
                ids, row_counts = frag.row_counts()
                if has_tanimoto:
                    # Row totals accumulate over every shard the row
                    # exists in, even where the src bitmap is empty —
                    # the tanimoto denominator needs the full row
                    # cardinality.
                    for rid, t in zip(ids, row_counts.tolist()):
                        row_totals[rid] = row_totals.get(rid, 0) + t
                seg = src.segments.get(shard)
                if seg is None:
                    continue
                if isinstance(seg, np.ndarray):
                    # host-tier filter: fused count against the host
                    # mirror, no device round trip
                    mids, matrix = frag.rows_matrix_host()
                    inter = np.bitwise_count(
                        matrix & seg[None, :]
                    ).sum(axis=1, dtype=np.int64)
                    ids = mids
                else:
                    inter = np.asarray(
                        bitops.count_rows(
                            frag.rows_device(ids) & seg[None, :]
                        )
                    )
                for rid, c in zip(ids, inter.tolist()):
                    if c:
                        counts[rid] = counts.get(rid, 0) + c

        if has_ids and ids_arg is not None:
            counts = {r: counts.get(r, 0) for r in ids_arg}
        if attr_name:
            wanted = set()
            if isinstance(attr_values, list):
                wanted = {v for v in attr_values}
            keep = {}
            for rid, c in counts.items():
                av = field.row_attrs.attrs(rid).get(attr_name)
                if av is not None and (not wanted or av in wanted):
                    keep[rid] = c
            counts = keep
        if has_tanimoto and src is not None:
            keep = {}
            for rid, c in counts.items():
                denom = row_totals.get(rid, 0) + src_count - c
                if denom > 0 and c * 100 >= tanimoto * denom:
                    keep[rid] = c
            counts = keep
        pairs = [
            Pair(id=rid, count=c)
            for rid, c in counts.items()
            if c >= threshold or has_ids
        ]
        pairs.sort(key=lambda p: (-p.count, p.id))
        if n and not has_ids:
            pairs = pairs[:n]
        return pairs

    # ------------------------------------------------------------------ Rows

    def _rows_of_field(
        self,
        field: Field,
        shards: list[int],
        views: list[str] | None = None,
    ) -> list[int]:
        """Sorted distinct row ids with at least one bit (reference
        fragment.go:2601-2712 rows())."""
        ids: set[int] = set()
        for vname in [VIEW_STANDARD] if views is None else views:
            v = field.view(vname)
            if v is None:
                continue
            for shard in shards:
                frag = v.fragment(shard)
                if frag is None:
                    continue
                rids, counts = frag.row_counts()
                ids.update(r for r, c in zip(rids, counts.tolist()) if c > 0)
        return sorted(ids)

    def _execute_rows(self, idx: Index, call: Call, shards: list[int] | None) -> RowIdentifiers:
        """reference executor.go:1277-1442 executeRows."""
        shards = self._shards_for(idx, shards)
        fname, ok = call.string_arg("_field")
        if not ok:
            raise ExecuteError("Rows() field required")
        field = idx.field(fname)
        if field is None:
            raise FieldNotFoundError(f"field not found: {fname}")
        views = self._rows_views(field, call)
        ids = self._rows_of_field(field, shards, views)

        col = call.args.get("column")
        if col is not None:
            col = self._maybe_translate_col(idx, col)
            shard = col // (field.n_words * 32)
            off = col % (field.n_words * 32)
            present: set[int] = set()
            for vname in [VIEW_STANDARD] if views is None else views:
                v = field.view(vname)
                if v is None:
                    continue
                frag = v.fragment(shard)
                if frag is None:
                    continue
                # one column-word gather per fragment, no per-row get_bit
                present.update(frag.rows_with_column(off))
            ids = sorted(set(ids) & present)

        prev, has_prev = call.uint_arg("previous")
        if has_prev:
            ids = [r for r in ids if r > prev]
        limit, has_limit = call.uint_arg("limit")
        if has_limit:
            ids = ids[:limit]
        return RowIdentifiers(rows=ids)

    def _rows_views(self, field: Field, call: Call) -> list[str] | None:
        """Time-bounded Rows: compute the view cover (reference
        executor.go:1342-1402)."""
        from_arg = call.args.get("from")
        to_arg = call.args.get("to")
        if from_arg is None and to_arg is None:
            return None
        cover = self._view_cover(field, from_arg, to_arg)
        return [] if cover is None else cover

    def _maybe_translate_col(self, idx: Index, col) -> int:
        if isinstance(col, str):
            if not idx.keys:
                raise ExecuteError("string column on unkeyed index")
            return self.translator.translate_key(idx.name, "", col)
        return int(col)

    # --------------------------------------------------------------- GroupBy

    def _execute_groupby(
        self, idx: Index, call: Call, shards: list[int] | None,
        filt_row=_UNSET,
    ) -> list[GroupCount]:
        """reference executor.go:1071-1275: nested cross-product of Rows()
        children, each level intersected with the previous.  ``filt_row``
        lets the batched BSI lane hand in a precomputed filter row (its
        Range filter rode a shared range_batch launch); the _UNSET
        default computes it from the call as before."""
        shards = self._shards_for(idx, shards)
        if not call.children:
            raise ExecuteError("GroupBy requires at least one Rows() child")
        for c in call.children:
            if c.name != "Rows":
                raise ExecuteError("GroupBy children must be Rows queries")
        limit, has_limit = call.uint_arg("limit")
        filt_call, has_filt = call.call_arg("filter")
        previous, has_prev = call.uint_slice_arg("previous")
        if has_prev and len(previous) != len(call.children):
            raise ExecuteError(
                "'previous' argument must have a value for each GroupBy field"
            )

        if filt_row is _UNSET:
            filt_row = (
                self._bitmap_call(idx, filt_call, shards) if has_filt else None
            )

        levels = []
        for c in call.children:
            fname = c.args.get("_field")
            field = idx.field(fname)
            if field is None:
                raise FieldNotFoundError(f"field not found: {fname}")
            row_ids = self._execute_rows(idx, c, shards).rows
            levels.append((fname, field, row_ids))

        results: list[GroupCount] = []
        use_limit = has_limit and limit > 0

        if not has_prev and all(
            f.view(VIEW_STANDARD) is not None for _, f, _ in levels
        ):
            fast = None
            if len(levels) == 2 and filt_row is None:
                # Two-level fast path: the pair-count kernel needs no
                # prefix masks at all (reference executor.go:3208-3211).
                fast = self._groupby_two_level_batch(idx, levels, shards)
            elif len(levels) >= 2:
                # k-level: one batched intersect-count launch per level
                # over running prefix masks, pruning empty combos.
                fast = self._groupby_k_level_batch(
                    idx, levels, shards, filt_row
                )
            if fast is not None:
                return fast[: limit if use_limit else len(fast)]

        # one device gather per (level, row), not per combination
        row_cache: dict[tuple[int, int], Row] = {}

        def level_row(level: int, rid: int) -> Row:
            key = (level, rid)
            if key not in row_cache:
                row_cache[key] = self._field_row(levels[level][1], rid, shards)
            return row_cache[key]

        def done() -> bool:
            return use_limit and len(results) >= limit

        def recurse(level: int, acc: Row | None, group: list[FieldRow], on_bound: bool):
            """Depth-first cross product in row order. ``on_bound`` tracks
            whether the prefix equals the `previous` page bound, in which
            case rows before the bound are skipped and the bound combo
            itself is excluded (reference executor.go:3127-3156 paging)."""
            if done():
                return
            fname, field, row_ids = levels[level]
            is_last = level + 1 == len(levels)
            for rid in row_ids:
                if done():
                    return
                bound_here = False
                if on_bound:
                    b = previous[level]
                    if rid < b:
                        continue
                    if rid == b:
                        if is_last:
                            continue  # strictly after the bound combo
                        bound_here = True
                row = level_row(level, rid)
                cur = row if acc is None else acc.intersect(row)
                g = group + [FieldRow(field=fname, row_id=rid)]
                if not is_last:
                    recurse(level + 1, cur, g, bound_here)
                else:
                    final = cur if filt_row is None else cur.intersect(filt_row)
                    cnt = final.count()
                    if cnt > 0:
                        results.append(GroupCount(group=g, count=cnt))

        recurse(0, None, [], has_prev)
        return results

    _GROUPBY_BATCH_MAX = 65536

    def _groupby_two_level_batch(
        self, idx: Index, levels, shards: list[int]
    ) -> list[GroupCount] | None:
        """All (row1, row2) combination counts in one launch; None when
        stacks are unavailable or the combo count is too large."""
        from pilosa_tpu.ops import kernels

        (f1name, f1, rows1), (f2name, f2, rows2) = levels
        n_combo = len(rows1) * len(rows2)
        if n_combo == 0:
            return []
        if n_combo > self._GROUPBY_BATCH_MAX:
            return None
        s1 = self._field_stack(f1, shards)
        s2 = self._field_stack(f2, shards) if f2 is not f1 else s1
        if s1 is None or s2 is None:
            return None
        slot1, bits1 = s1
        slot2, bits2 = s2
        present1 = [r for r in rows1 if r in slot1]
        present2 = [r for r in rows2 if r in slot2]
        if not present1 or not present2:
            return []
        with tracing.start_span("executor.groupByBatch").set_tag(
            "n", len(present1) * len(present2)
        ):
            # The full combination matrix is one cross-field gram scan on
            # the MXU (kernels.cross_gram_xla); the batched AND+popcount
            # kernels remain the fallback when the gram declines.
            counts2d = None
            if f2 is f1:
                uniq = sorted({slot1[r] for r in present1 + present2})
                g, pos = self._field_gram(f1, bits1, uniq)
                if g is not None:
                    pa = np.array([pos[slot1[r]] for r in present1])
                    pb = np.array([pos[slot1[r]] for r in present2])
                    counts2d = g[np.ix_(pa, pb)]
            else:
                counts2d = self._cross_gram(
                    f1,
                    bits1,
                    f2,
                    bits2,
                    [slot1[r] for r in present1],
                    [slot2[r] for r in present2],
                )
            if counts2d is not None:
                counts = counts2d.reshape(-1)
            else:
                # wide pair batches (> GRAM_MAX_ROWS distinct rows):
                # local stacks return [B, S] partials; process-spanning
                # stacks return replicated int64[B] in-program psum
                # totals (kernels.py r05 — the fast lane no longer
                # declines across hosts)
                if not kernels.row_counts_supported(bits1) or (
                    f2 is not f1
                    and not kernels.row_counts_supported(bits2)
                ):
                    # spanning mesh too large even for the chunked
                    # psum: decline so the recursive path answers it
                    return None
                combos_s = [
                    (slot1[r1], slot2[r2])
                    for r1 in present1
                    for r2 in present2
                ]
                B = _pow2(len(combos_s))
                if B > len(combos_s):
                    # pow2 batch pad: padded vs useful per-shard partials
                    kernels.note_pad(
                        "pair_count",
                        B * bits1.shape[0] * 4,
                        len(combos_s) * bits1.shape[0] * 4,
                    )
                ras = np.zeros(B, dtype=np.int32)
                rbs = np.zeros(B, dtype=np.int32)
                for j, (sa, sb) in enumerate(combos_s):
                    ras[j], rbs[j] = sa, sb
                if f2 is f1:
                    partials = kernels.pair_count_batched(
                        bits1, jnp.asarray(ras), jnp.asarray(rbs)
                    )
                else:
                    partials = kernels.pair_count_two_batched(
                        bits1, bits2, jnp.asarray(ras), jnp.asarray(rbs)
                    )
                partials = np.asarray(partials).astype(np.int64)
                counts = (
                    partials if partials.ndim == 1
                    else partials.sum(axis=1)
                )
        out = []
        for j, (r1, r2) in enumerate(
            (r1, r2) for r1 in present1 for r2 in present2
        ):
            c = int(counts[j])
            if c > 0:
                out.append(
                    GroupCount(
                        group=[
                            FieldRow(field=f1name, row_id=r1),
                            FieldRow(field=f2name, row_id=r2),
                        ],
                        count=c,
                    )
                )
        return out

    @staticmethod
    def _row_to_shard_matrix(row: Row, shards: list[int], S: int, W: int) -> np.ndarray:
        """A Row's per-shard segments as a dense ``uint32[S, W]`` matrix
        aligned to a stack's (padded) shard axis; absent shards are
        zero."""
        filt = np.zeros((S, W), dtype=np.uint32)
        for si, s in enumerate(shards):
            seg = row.segments.get(s)
            if seg is not None:
                filt[si] = np.asarray(seg)
        return filt

    # prefix-mask memory ceiling for the k-level GroupBy batch
    _GROUPBY_PREFIX_BUDGET_BYTES = 256 << 20

    def _groupby_k_level_batch(
        self, idx: Index, levels, shards: list[int], filt_row
    ) -> list[GroupCount] | None:
        """All k-level combination counts with O(1) launches per level:
        maintain [C, S, W] intersection masks for surviving combos, count
        every (combo, next-row) pair in one scan launch, prune zeros,
        refine. None when stacks are unavailable or the surviving combo
        set would exceed the prefix budget (callers fall back to the
        recursive path). Matches reference semantics executor.go:3057-3230
        (DFS row order, count = intersection of all levels + filter)."""
        from pilosa_tpu.ops import kernels

        stacks = []
        for _, f, _ in levels:
            st = self._field_stack(f, shards)
            if st is None:
                return None
            stacks.append(st)
        slot0, bits0 = stacks[0]
        if kernels.stack_spans_processes(bits0):
            # combo-count kernels return per-shard partials, not host
            # addressable on a spanning stack; recursive path serves
            return None
        S, _, W = bits0.shape
        cmax = max(1, self._GROUPBY_PREFIX_BUDGET_BYTES // (S * W * 4))

        rows1 = [r for r in levels[0][2] if r in slot0]
        if not rows1:
            return []
        if len(rows1) > cmax or len(rows1) > self._GROUPBY_BATCH_MAX:
            return None
        prefix = kernels.gather_prefix(
            bits0, jnp.asarray([slot0[r] for r in rows1], jnp.int32)
        )
        if filt_row is not None:
            filt = self._row_to_shard_matrix(filt_row, shards, S, W)
            prefix = prefix & jnp.asarray(filt)[None]
        combos: list[tuple[int, ...]] = [(r,) for r in rows1]

        with tracing.start_span("executor.groupByKLevel").set_tag(
            "levels", len(levels)
        ):
            for li in range(1, len(levels)):
                slotL, bitsL = stacks[li]
                rows = [r for r in levels[li][2] if r in slotL]
                if not rows:
                    return []
                idxL = jnp.asarray([slotL[r] for r in rows], jnp.int32)
                # MXU cross gram when safe (one prefix read per level);
                # per-shard scan partials otherwise
                counts = kernels.combo_counts_gram(prefix, bitsL, idxL)
                if counts is None:
                    counts = np.asarray(
                        kernels.combo_counts(prefix, bitsL, idxL)
                    ).astype(np.int64).sum(axis=2)  # [C, Rl]
                live = np.argwhere(counts > 0)  # row-major: DFS order
                if li == len(levels) - 1:
                    out = []
                    for ci, ri in live:
                        out.append(
                            GroupCount(
                                group=[
                                    FieldRow(
                                        field=levels[k][0], row_id=rid
                                    )
                                    for k, rid in enumerate(
                                        combos[ci] + (rows[ri],)
                                    )
                                ],
                                count=int(counts[ci, ri]),
                            )
                        )
                    return out
                if len(live) == 0:
                    return []
                if len(live) > cmax or len(live) > self._GROUPBY_BATCH_MAX:
                    return None
                prefix = kernels.refine_prefix(
                    prefix,
                    bitsL,
                    jnp.asarray(live[:, 0], jnp.int32),
                    jnp.asarray(
                        [slotL[rows[ri]] for ri in live[:, 1]], jnp.int32
                    ),
                )
                combos = [
                    combos[ci] + (rows[ri],) for ci, ri in live
                ]
        return []

    # --------------------------------------------------------------- Options

    def _execute_options(self, idx: Index, call: Call, shards: list[int] | None) -> Any:
        """reference executor.go:344-406 executeOptionsCall."""
        if len(call.children) != 1:
            raise ExecuteError("Options() requires exactly one child")
        exclude_columns, _ = call.bool_arg("excludeColumns")
        exclude_row_attrs, _ = call.bool_arg("excludeRowAttrs")
        column_attrs, _ = call.bool_arg("columnAttrs")
        shards_arg, has_shards = call.uint_slice_arg("shards")
        if has_shards:
            shards = shards_arg
        result = self._execute_call(idx, call.children[0], shards)
        if isinstance(result, Row):
            if exclude_columns:
                result.segments = {}
            if exclude_row_attrs:
                result.attrs = {}
            if column_attrs:
                result.attrs["columnattrs"] = [
                    {"id": int(c), "attrs": idx.column_attrs.attrs(int(c))}
                    for c in result.columns()
                    if idx.column_attrs.attrs(int(c))
                ]
        return result
