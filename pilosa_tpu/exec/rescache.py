"""Semantic result cache with version-precise invalidation
(docs/caching.md).

The cache answers a repeated read query without re-dispatching kernels
when — and only when — none of the fragments the query reads have
changed.  An entry is keyed by the *semantics* of the call (canonical
serialization of the translated AST, commutative children sorted), the
shard restriction, and the index's schema generation; its validity is
carried by a **version vector**: the sorted tuple of
``(field, view, shard, epoch, version)`` over every fragment the call
can read.  ``Fragment.version`` is bumped on every point write, bulk
import, and host-row load and never resets (snapshot compaction resets
the op log, not the version), and ``Fragment.epoch`` is process-unique
per fragment object, so a shard that migrates away and back during a
resize can never alias an old vector.

Invalidation is therefore *precise and lazy*: a lookup recomputes the
current vector and a mismatch is a miss (counted as an invalidation —
the stale entry is dropped).  Writes additionally invalidate *eagerly*
through :meth:`ResultCache.note_write`, which drops only the entries
whose field set intersects the written field — this is what keeps
attribute writes (``SetRowAttrs``), which do not bump fragment
versions, from serving stale attrs, and what makes the
``rescache_invalidations`` metric mean "entries a write actually
killed", never "cache cleared".

Hot TopN/GroupBy entries **promote** to maintained views: instead of
dropping on a version mismatch, a promoted entry refreshes itself
through its ``recompute`` closure — for unfiltered TopN that closure
re-merges the per-fragment maintained row counts (``Fragment._counts``,
updated by ingest in the same group-commit as the bits), which costs a
host reduce, not a device launch.  When the accumulated write delta
(the version-sum drift since promotion) exceeds ``demote_deltas`` the
entry demotes back to ordinary cache-on-miss and the next miss rebuilds
it from scratch.

Thread safety: one lock around the table; results are copied on hit
(:func:`copy_result`) so callers can attach keys/attrs without
mutating the cached object.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable

from pilosa_tpu.core.index import Index
from pilosa_tpu.exec.result import (
    FieldRow,
    GroupCount,
    Pair,
    Row,
    RowIdentifiers,
    ValCount,
)
from pilosa_tpu.obs import qprofile
from pilosa_tpu.obs import stats as stats_mod
from pilosa_tpu.pql.ast import Call

# Sentinel distinct from every result value (None and False are results).
MISS = object()

# Read-only call shapes whose results are a pure function of fragment
# contents + the translated AST.  Anything else (writes, Options,
# attr-driven shapes) bypasses the cache.
_CACHEABLE = {
    "All",
    "Count",
    "Difference",
    "GroupBy",
    "Intersect",
    "Max",
    "MaxRow",
    "Min",
    "MinRow",
    "Not",
    "Range",
    "Row",
    "Rows",
    "Sum",
    "TopN",
    "Union",
    "Xor",
}

# Children of these ops are order-independent: canonical form sorts them
# so Intersect(A, B) and Intersect(B, A) share one entry.
_COMMUTATIVE = {"Intersect", "Union", "Xor"}

# Calls whose result depends on row/column attributes, which live
# outside the fragment version space.  TopN(attrName=...) filters by
# attrs; never cache it.
_ATTR_ARGS = ("attrName", "attrValues")

_EXISTENCE = "_exists"


def canonical_str(call: Call) -> str:
    """Deterministic serialization of a call: args render sorted-key
    (``Call.__str__`` already guarantees that) and commutative children
    render in sorted canonical order."""
    kids = [canonical_str(c) for c in call.children]
    if call.name in _COMMUTATIVE:
        kids.sort()
    parts = list(kids)
    rendered = str(Call(call.name, call.args, []))
    inner = rendered[len(call.name) + 1 : -1]
    if inner:
        parts.append(inner)
    return f"{call.name}({', '.join(parts)})"


def subtree_key(idx: Index, call: Call) -> str | None:
    """Canonical CSE key for one subtree, or None when the subtree is
    not safely shareable — the exact cacheability rules whole-call
    entries use (recognized read-only shapes, no attr args), so a
    flight-shared operand (exec/planner.py) is valid under precisely
    the per-fragment version vector a cache entry would carry."""
    if collect_fields(idx, call) is None:
        return None
    return canonical_str(call)


def collect_fields(idx: Index, call: Call) -> set[str] | None:
    """The field names a call can read, or None when the call shape is
    not cacheable.  Conservative: an unrecognized name anywhere in the
    tree poisons the whole call."""
    if call.name not in _CACHEABLE:
        return None
    for a in _ATTR_ARGS:
        if a in call.args:
            return None
    fields: set[str] = set()
    if call.name in ("Not", "All"):
        # existence-backed shapes read the internal _exists field
        fields.add(_EXISTENCE)
    fv = call.args.get("_field")
    if isinstance(fv, str):
        fields.add(fv)
    f = call.args.get("field")
    if isinstance(f, str):
        fields.add(f)
    fa = call.field_arg()
    if fa is not None and idx.field(fa) is not None:
        fields.add(fa)
    for child in call.children:
        sub = collect_fields(idx, child)
        if sub is None:
            return None
        fields |= sub
    filt = call.args.get("filter")
    if isinstance(filt, Call):
        sub = collect_fields(idx, filt)
        if sub is None:
            return None
        fields |= sub
    return fields


def version_vector(
    idx: Index, fields: set[str], shards: list[int] | None
) -> tuple:
    """Sorted ``(field, view, shard, epoch, version)`` over every
    fragment the fields expose in the shard scope.  Covers ALL views of
    each field (time-quantum Range reads quantum views) — coarser than
    the minimal read set but always a superset, so staleness can only
    cause a spurious miss, never a stale hit."""
    scope = set(shards) if shards is not None else None
    vec = []
    for fname in fields:
        field = idx.field(fname)
        if field is None:
            continue
        for vname in sorted(field.views):
            view = field.views[vname]
            for shard, frag in sorted(view.fragments.items()):
                if scope is not None and shard not in scope:
                    continue
                vec.append((fname, vname, shard, frag.epoch, frag.version))
    return tuple(sorted(vec))


def _version_sum(vec: tuple) -> int:
    return sum(item[-1] for item in vec)


def copy_result(result: Any) -> Any:
    """A hit-side copy shallow enough to be cheap and deep enough that
    the caller's result translation (keys/attrs attachment) never
    mutates the cached object.  Segment arrays are shared — the
    executor treats them as immutable."""
    if isinstance(result, Row):
        out = Row(dict(result.segments), result.n_words)
        out.attrs = dict(result.attrs)
        return out
    if isinstance(result, Pair):
        return Pair(result.id, result.key, result.count)
    if isinstance(result, ValCount):
        return ValCount(result.value, result.count)
    if isinstance(result, RowIdentifiers):
        return RowIdentifiers(list(result.rows), None)
    if isinstance(result, GroupCount):
        return GroupCount(
            [FieldRow(g.field, g.row_id, None) for g in result.group],
            result.count,
        )
    if isinstance(result, list):
        return [copy_result(r) for r in result]
    # int / bool / None / str scalars
    return result


class _Token:
    """A cacheable miss: carries the key and the vector captured BEFORE
    execution, so a write landing mid-compute can never be masked (the
    stored vector predates it and the next lookup misses)."""

    __slots__ = ("key", "vector", "fields", "index_name")

    def __init__(self, key, vector, fields, index_name):
        self.key = key
        self.vector = vector
        self.fields = fields
        self.index_name = index_name


class _Entry:
    __slots__ = (
        "vector",
        "result",
        "hits",
        "fields",
        "index_name",
        "recompute",
        "maintained",
        "delta_accum",
    )

    def __init__(self, vector, result, fields, index_name, recompute):
        self.vector = vector
        self.result = result
        self.hits = 0
        self.fields = fields
        self.index_name = index_name
        self.recompute = recompute
        self.maintained = False
        self.delta_accum = 0


class ResultCache:
    """Bounded (LRU) semantic result cache.  One instance per Executor;
    the distributed layer reuses it for per-owner partials through the
    ``*_raw`` entry points."""

    def __init__(
        self,
        entries: int = 512,
        promote_hits: int = 3,
        demote_deltas: int = 64,
        stats=None,
        stats_fn: Callable[[], Any] | None = None,
    ):
        self.max_entries = int(entries)
        self.promote_hits = int(promote_hits)
        self.demote_deltas = int(demote_deltas)
        # stats_fn defers the client read: the holder installs its real
        # client after the executor (and this cache) are constructed
        self._stats = stats if stats is not None else stats_mod.NOP
        self._stats_fn = stats_fn
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        # (index, field) -> set of entry keys reading that field, for
        # eager write invalidation
        self._by_field: dict[tuple[str, str], set] = {}
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.promotions = 0
        self.demotions = 0
        self.maintained_hits = 0
        self.degraded_hits = 0
        self.stores = 0
        self.evictions = 0

    @property
    def stats(self):
        return self._stats_fn() if self._stats_fn is not None else self._stats

    def set_stats(self, client) -> None:
        self._stats = client
        self._stats_fn = None

    # ------------------------------------------------------ key plumbing

    @staticmethod
    def _key(idx: Index, call: Call, shards: list[int] | None) -> tuple:
        return (
            idx.name,
            idx.seq,
            idx.generation,
            canonical_str(call),
            tuple(sorted(shards)) if shards is not None else None,
        )

    # ----------------------------------------------------------- lookups

    def lookup(
        self, idx: Index, call: Call, shards: list[int] | None
    ) -> tuple[Any, _Token | None]:
        """Returns ``(result, None)`` on a hit, ``(MISS, token)`` on a
        cacheable miss (pass the token to :meth:`store` after
        computing), and ``(MISS, None)`` when the call is uncacheable."""
        fields = collect_fields(idx, call)
        if not fields:
            return MISS, None
        vec = version_vector(idx, fields, shards)
        if not vec:
            return MISS, None
        key = self._key(idx, call, shards)
        with qprofile.span("rescache.lookup", call=call.name):
            return self._probe_locked(key, vec, fields, idx.name)

    def lookup_stale(
        self, idx: Index, call: Call, shards: list[int] | None
    ) -> Any:
        """Degraded-tier lookup (server/qos.py pressure stage 2): the
        LAST-KNOWN result for this exact canonical call, version check
        waived.  Maintained entries refresh through writes, so the
        served answer is usually current anyway; a plain entry may be
        stale — that is the explicit contract of the degraded tier and
        the response is marked.  Never mutates promotion/invalidation
        bookkeeping: the degraded path must not distort the cache's
        steady-state policy.  Returns :data:`MISS` when no entry
        exists."""
        key = self._key(idx, call, shards)
        with qprofile.span("rescache.lookupStale", call=call.name):
            with self._lock:
                entry = self._entries.get(key)
                if entry is None:
                    return MISS
                self.degraded_hits += 1
                self.stats.count("rescache_degraded_hits", 1)
                return copy_result(entry.result)

    def probe_raw(self, key: tuple, vector: tuple) -> Any:
        """Distributed partial probe: explicit key + precomputed vector
        (which the caller captured before dispatch).  Returns the
        result or :data:`MISS`."""
        with qprofile.span("rescache.lookup", raw=True):
            res, _tok = self._probe_locked(key, vector, None, None)
        return res

    def _probe_locked(self, key, vec, fields, index_name):
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.vector == vec:
                self._entries.move_to_end(key)
                entry.hits += 1
                self.hits += 1
                self.stats.count("rescache_hits", 1)
                if (
                    entry.recompute is not None
                    and not entry.maintained
                    and entry.hits >= self.promote_hits
                ):
                    entry.maintained = True
                    self.promotions += 1
                    self.stats.count("rescache_promotions", 1)
                return copy_result(entry.result), None
            if entry is not None:
                # stale — refresh maintained entries in place, drop the
                # rest (that drop IS the precise invalidation)
                refreshed = self._refresh_locked(key, entry, vec)
                if refreshed is not MISS:
                    return refreshed, None
            self.misses += 1
            self.stats.count("rescache_misses", 1)
            return MISS, _Token(key, vec, fields, index_name)

    def _refresh_locked(self, key, entry: _Entry, vec) -> Any:
        """Serve a promoted entry through a version change by
        recomputing from the maintained counts; demote when the write
        drift exceeds the rebuild threshold.  Returns MISS when the
        entry was dropped instead."""
        if entry.maintained and entry.recompute is not None:
            drift = _version_sum(vec) - _version_sum(entry.vector)
            entry.delta_accum += max(drift, 1)
            if entry.delta_accum <= self.demote_deltas:
                recompute = entry.recompute
                # recompute outside the lock: it reads fragments, which
                # may contend with writers holding fragment locks
                self._lock.release()
                try:
                    fresh = recompute()
                except Exception:
                    fresh = None
                finally:
                    self._lock.acquire()
                if fresh is not None and self._entries.get(key) is entry:
                    entry.result = fresh
                    entry.vector = vec
                    entry.hits += 1
                    self.maintained_hits += 1
                    self.hits += 1
                    self.stats.count("rescache_hits", 1)
                    self.stats.count("rescache_maintained_hits", 1)
                    return copy_result(fresh)
                return MISS
            self.demotions += 1
            self.stats.count("rescache_demotions", 1)
        self._drop_locked(key, entry)
        self.invalidations += 1
        self.stats.count("rescache_invalidations", 1)
        return MISS

    # ------------------------------------------------------------ stores

    def store(
        self,
        token: _Token,
        result: Any,
        recompute: Callable[[], Any] | None = None,
    ) -> None:
        """Install a computed result under the pre-execution vector the
        token captured."""
        if token is None or isinstance(result, BaseException):
            return
        entry = _Entry(
            token.vector, result, token.fields, token.index_name, recompute
        )
        self._install(token.key, entry)

    def store_raw(
        self,
        key: tuple,
        vector: tuple,
        result: Any,
        index_name: str | None = None,
        fields: set[str] | None = None,
    ) -> None:
        if isinstance(result, BaseException):
            return
        self._install(key, _Entry(vector, result, fields, index_name, None))

    def _install(self, key, entry: _Entry) -> None:
        with self._lock:
            old = self._entries.get(key)
            if old is not None:
                # keep promotion heat across rebuilds of the same key
                entry.hits = old.hits
                entry.maintained = old.maintained
                entry.recompute = entry.recompute or old.recompute
                self._drop_locked(key, old)
            self._entries[key] = entry
            if entry.fields and entry.index_name is not None:
                for fname in entry.fields:
                    self._by_field.setdefault(
                        (entry.index_name, fname), set()
                    ).add(key)
            self.stores += 1
            while len(self._entries) > self.max_entries:
                ev_key, ev_entry = self._entries.popitem(last=False)
                self._unindex_locked(ev_key, ev_entry)
                self.evictions += 1
                self.stats.count("rescache_evictions", 1)

    def _drop_locked(self, key, entry: _Entry) -> None:
        self._entries.pop(key, None)
        self._unindex_locked(key, entry)

    def _unindex_locked(self, key, entry: _Entry) -> None:
        if not entry.fields or entry.index_name is None:
            return
        for fname in entry.fields:
            keys = self._by_field.get((entry.index_name, fname))
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del self._by_field[(entry.index_name, fname)]

    # ------------------------------------------------------ invalidation

    def note_write(self, index_name: str, field_name: str | None) -> None:
        """Eager, precise invalidation: drop exactly the entries whose
        field set intersects the written field (all of the index's
        entries when ``field_name`` is None — column-attr writes).
        Maintained entries survive — their next lookup refreshes from
        the maintained counts instead."""
        with self._lock:
            if field_name is None:
                keys = [
                    k
                    for (iname, _f), ks in self._by_field.items()
                    if iname == index_name
                    for k in ks
                ]
            else:
                keys = list(
                    self._by_field.get((index_name, field_name), ())
                )
            for key in keys:
                entry = self._entries.get(key)
                if entry is None or entry.maintained:
                    continue
                self._drop_locked(key, entry)
                self.invalidations += 1
                self.stats.count("rescache_invalidations", 1)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_field.clear()

    # ------------------------------------------------------ introspection

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        """The /debug/vars block (server/http.py r_debug_vars)."""
        with self._lock:
            maintained = sum(
                1 for e in self._entries.values() if e.maintained
            )
            return {
                "entries": len(self._entries),
                "maxEntries": self.max_entries,
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "promotions": self.promotions,
                "demotions": self.demotions,
                "maintainedHits": self.maintained_hits,
                "maintainedEntries": maintained,
                "degradedHits": self.degraded_hits,
                "stores": self.stores,
                "evictions": self.evictions,
                "promoteHits": self.promote_hits,
                "demoteDeltas": self.demote_deltas,
            }
