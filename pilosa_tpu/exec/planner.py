"""Flight-level query planner: cross-query CSE, cost-based reordering,
and measured lane choice (docs/serving.md "Flight planning").

The continuous-batching plane (server/batcher.py) coalesces concurrent
queries into flights, but before this module every flight-mate's tree
was evaluated independently — a dashboard fan-in where 50 queries share
the same ``Intersect(Row(...), Row(...))`` filter paid for that operand
50 times per flight.  The planner runs once per flight shard-group
inside ``Executor.execute_batch``, after the semantic-cache probe and
before the batched device passes, and applies three transformations:

**Flight-level CSE** — every eligible subtree is hashed by its rescache
canonical form (commutative children sorted, exec/rescache.py).  A
canonical form occurring two or more times across the flight is
evaluated ONCE through :meth:`Executor.cached_execute_call` — so the
materialized row rides the same per-fragment ``(epoch, version)``
vector the result cache tracks, which is what keeps sharing correct
under concurrent ingest — and the row is grafted into each consumer as
an internal ``__shared__`` node.  Grafted trees deliberately fall off
the compiled astbatch path (``match_tree`` returns None for the
unknown name) onto host segment algebra: the flight pays one subtree
evaluation plus N cheap combines instead of N full evaluations.

**Cost-based reordering** — children of commutative operators
(``Intersect``/``Union``/``Xor``, and the subtrahend tail of
``Difference``) are reordered cheapest-first using per-fragment
density stats cached per fragment version (``Fragment.
container_profile`` — the same numbers ``/debug/fragments`` reports),
so the host fold short-circuits early: ``Executor._combine`` stops an
Intersect the moment the running row is provably empty.  Reordering
never changes cache keys: canonical forms sort commutative children
anyway, and lookup tokens are captured before planning runs.

**Measured lane choice** — the gram-vs-host-scan and batch-vs-solo
warm-up gates (``_PAIR_SINGLE_WARM``, the ``demand >= 2`` stack gate)
are overridden by measured prices once the device cost ledger has
samples: the device lane's per-sig-class EWMA device-ms
(``devledger.measured_ms``) against the host lane's EWMA wall-ms noted
by the executor's latency tier.  Until BOTH lanes have
``MIN_SAMPLES`` the hardcoded heuristics stand — cache-vs-compute
stays always-cache (a rescache hit is strictly cheaper than any lane).

Observability: decisions surface as ``planner.cse`` / ``planner.
reorder`` spans under ``?profile=true``, ``pilosa_planner_{cse_hits,
reorders,lane_overrides}`` series in ``/metrics`` (booked through the
holder stats client like rescache's counters), a ``planner`` block in
``/debug/vars``, and per-flight deltas annotated by the batcher.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from pilosa_tpu.core.index import Index
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec import rescache
from pilosa_tpu.obs import devledger, qprofile
from pilosa_tpu.pql.ast import Call

# Internal graft node name: never parseable from PQL, unknown to both
# astbatch.match_tree (declines to host algebra — intended) and
# rescache.collect_fields (uncacheable — a grafted tree can never leak
# into a cache entry's key or a maintained recompute closure).
SHARED = "__shared__"

# Subtree shapes worth sharing: operator nodes whose evaluation combines
# children (a bare Row is as cheap to re-read as to graft).
_CSE_OPS = {"Intersect", "Union", "Difference", "Xor", "Not"}

# Fully-commutative operators; Difference commutes only past its head.
_COMMUTATIVE = {"Intersect", "Union", "Xor"}

# Unpriceable subtrees sort last (stable), never first.
_UNKNOWN_COST = float("inf")


def make_shared(row) -> Call:
    """A graft node carrying a materialized Row.  The row rides as an
    instance attribute, NOT an arg: ``Call.__str__`` renders args, and a
    Row must never leak into a serialized form."""
    node = Call(SHARED)
    node._planner_row = row
    return node


def shared_row(call: Call):
    """The materialized Row a graft node carries (Executor._bitmap_call
    copies it before segment algebra, like a cache hit)."""
    return call._planner_row


def contains_shared(call: Call) -> bool:
    """Whether a tree holds any graft node — lane-choice wall-ms notes
    skip such trees (a post-CSE combine is not a solo-evaluation price)."""
    if call.name == SHARED:
        return True
    return any(contains_shared(c) for c in call.children)


class LaneChooser:
    """Measured gram-vs-scan / batch-vs-solo arbitration.

    The device lane's price comes from the cost ledger's per-sig-class
    EWMA device-ms (obs/devledger.py); the host lane's price is noted
    here by the executor's latency tier.  ``decide`` keeps the caller's
    heuristic until both lanes have ``MIN_SAMPLES`` — a cold ledger
    must never flip behavior — then picks the cheaper lane, counting an
    override whenever that differs from what the heuristic chose."""

    MIN_SAMPLES = 4
    _ALPHA = 0.25

    # op class -> the ledger (site, sig class) that prices its device lane
    DEVICE_SOURCES = {
        "pair_count": ("executor.pair_counts", "gram"),
        "tree_count": ("exec.astbatch", "count"),
    }

    def __init__(self):
        self._lock = threading.Lock()
        self._host: dict[str, list] = {}  # op class -> [n, EWMA wall-ms]

    def note_host(self, op_class: str, wall_ms: float) -> None:
        with self._lock:
            row = self._host.get(op_class)
            if row is None:
                self._host[op_class] = [1, wall_ms]
            else:
                row[0] += 1
                row[1] += self._ALPHA * (wall_ms - row[1])

    def prefer_device(self, op_class: str) -> bool | None:
        """True/False once both lanes are priced; None = no opinion."""
        src = self.DEVICE_SOURCES.get(op_class)
        if src is None:
            return None
        dev = devledger.measured_ms(*src)
        if dev is None or dev[0] < self.MIN_SAMPLES:
            return None
        with self._lock:
            host = self._host.get(op_class)
            if host is None or host[0] < self.MIN_SAMPLES:
                return None
            return dev[1] <= host[1]

    def snapshot(self) -> dict:
        with self._lock:
            host = {
                cls: {"samples": row[0], "ewmaMs": round(row[1], 4)}
                for cls, row in sorted(self._host.items())
            }
        device = {}
        for cls, src in self.DEVICE_SOURCES.items():
            m = devledger.measured_ms(*src)
            if m is not None:
                device[cls] = {"launches": m[0], "ewmaMs": round(m[1], 4)}
        return {"host": host, "device": device}


class FlightPlanner:
    """One planner per Executor; all counters are monotonic (the batcher
    snapshots them around a flight to annotate per-flight deltas)."""

    def __init__(self, executor, enabled: bool = True):
        self.executor = executor
        self.enabled = enabled
        self.lanes = LaneChooser()
        self._lock = threading.Lock()
        # consumers served from a flight-shared evaluation beyond the
        # first (the CSE analogue of a cache hit)
        self.cse_hits = 0
        # distinct canonical subtrees materialized once per flight
        self.cse_shared = 0
        # operator nodes whose child order actually changed
        self.reorders = 0
        # lane decisions that contradicted the warm-up heuristic
        self.lane_overrides = 0
        # planning passes that degraded to unplanned execution
        self.errors = 0

    # ------------------------------------------------------------- stats

    def _count(self, counter: str, n: int = 1) -> None:
        if not n:
            return
        with self._lock:
            setattr(self, counter, getattr(self, counter) + n)
        stats = getattr(self.executor.holder, "stats", None)
        if stats is not None:
            # same client pattern as rescache: surfaces as
            # pilosa_planner_<counter> in /metrics
            stats.count(f"planner_{counter}", n)

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "enabled": self.enabled,
                "cseHits": self.cse_hits,
                "cseShared": self.cse_shared,
                "reorders": self.reorders,
                "laneOverrides": self.lane_overrides,
                "errors": self.errors,
            }
        snap["lanes"] = self.lanes.snapshot()
        return snap

    # -------------------------------------------------------- lane choice

    def choose_lane(self, op_class: str, heuristic: bool) -> bool:
        """The engage/decline verdict for a device-lane gate: measured
        price when both lanes are sampled, the caller's heuristic
        otherwise."""
        if not self.enabled:
            return heuristic
        pref = self.lanes.prefer_device(op_class)
        if pref is None:
            return heuristic
        if pref != heuristic:
            self._count("lane_overrides")
        return pref

    def note_host_lane(self, op_class: str, wall_ms: float) -> None:
        if self.enabled:
            self.lanes.note_host(op_class, wall_ms)

    # ----------------------------------------------------------- planning

    def plan_group(
        self,
        idx: Index,
        calls: list[Call],
        shards: list[int] | None,
        results: list[Any],
        unset: Any,
    ) -> None:
        """Plan one shard-group of a flight in place: reorder commutative
        children cheapest-first, then share canonical subtrees.  Runs
        AFTER the rescache probe (tokens/keys are already captured, so
        mutation here cannot shift cache identity) and BEFORE the batch
        passes (grafted trees must decline them).  Any planning failure
        degrades that transformation to a no-op — the flight still
        executes unplanned."""
        if not self.enabled:
            return
        try:
            t0 = time.perf_counter()
            reorders = self._reorder_pass(idx, calls, shards, results, unset)
            if reorders:
                qprofile.annotate(
                    "planner.reorder",
                    (time.perf_counter() - t0) * 1e3,
                    reorders=reorders,
                )
        except Exception:
            self._count("errors")
        try:
            t0 = time.perf_counter()
            shared, hits = self._cse_pass(
                idx, calls, shards, results, unset
            )
            if shared:
                qprofile.annotate(
                    "planner.cse",
                    (time.perf_counter() - t0) * 1e3,
                    shared=shared,
                    hits=hits,
                )
        except Exception:
            self._count("errors")

    # -- cost-based reordering --------------------------------------------

    def _reorder_pass(self, idx, calls, shards, results, unset) -> int:
        shard_list = self.executor._shards_for(idx, shards)
        cache: dict[str, tuple[int, int]] = {}
        changed = 0
        for i, call in enumerate(calls):
            if results[i] is not unset:
                continue
            root = None
            if call.name in _CSE_OPS:
                root = call
            elif call.name == "Count" and len(call.children) == 1:
                root = call.children[0]
            if root is not None:
                changed += self._reorder_tree(idx, root, shard_list, cache)
        self._count("reorders", changed)
        return changed

    def _reorder_tree(self, idx, node, shard_list, cache) -> int:
        if node.name == SHARED:
            return 0
        changed = 0
        for c in node.children:
            changed += self._reorder_tree(idx, c, shard_list, cache)
        kids = node.children
        if node.name in _COMMUTATIVE and len(kids) > 1:
            order = self._cost_order(idx, kids, shard_list, cache)
            if order != list(range(len(kids))):
                node.children = [kids[j] for j in order]
                changed += 1
        elif node.name == "Difference" and len(kids) > 2:
            order = self._cost_order(idx, kids[1:], shard_list, cache)
            if order != list(range(len(kids) - 1)):
                node.children = [kids[0]] + [kids[1 + j] for j in order]
                changed += 1
        return changed

    def _cost_order(self, idx, kids, shard_list, cache) -> list[int]:
        costs = [
            self._subtree_cost(idx, c, shard_list, cache) for c in kids
        ]
        # stable: original position breaks ties, so equal-cost flights
        # reorder identically and compiled sigs stay put
        return sorted(range(len(kids)), key=lambda j: (costs[j], j))

    def _subtree_cost(self, idx, call, shard_list, cache) -> float:
        """Expected result mass of a subtree, from version-cached
        fragment density stats — a selectivity proxy, not a latency
        model: Intersect is bounded by its sparsest child, Union/Xor
        accumulate, Difference is bounded by its head."""
        name = call.name
        if name == SHARED:
            # already materialized: free to combine, so it sorts first
            # and empty shared rows short-circuit the whole fold
            return 0.0
        if name in ("Row", "Range"):
            fname = call.args.get("_field") or call.field_arg()
            if not isinstance(fname, str):
                return _UNKNOWN_COST
            bits, rows = self._field_mass(idx, fname, shard_list, cache)
            if call.has_conditions():
                # a BSI predicate can select any fraction of the column
                # space; price the full field mass
                return float(bits)
            # one plain row: the field's average row density
            return bits / rows if rows else 0.0
        if name in ("Not", "All"):
            bits, _ = self._field_mass(idx, "_exists", shard_list, cache)
            return float(bits)
        if name in _COMMUTATIVE or name == "Difference":
            kid_costs = [
                self._subtree_cost(idx, c, shard_list, cache)
                for c in call.children
            ]
            if not kid_costs:
                return _UNKNOWN_COST
            if name == "Intersect":
                return min(kid_costs)
            if name == "Difference":
                return kid_costs[0]
            return sum(kid_costs)
        return _UNKNOWN_COST

    def _field_mass(self, idx, fname, shard_list, cache):
        """(set bits, materialized rows) over one field's fragments for
        the shard list, from the per-version container_profile cache."""
        hit = cache.get(fname)
        if hit is not None:
            return hit
        bits = rows = 0
        field = idx.field(fname)
        if field is not None:
            vname = (
                field.bsi_view_name() if field.is_bsi() else VIEW_STANDARD
            )
            view = field.view(vname)
            if view is not None:
                for s in shard_list:
                    frag = view.fragment(s)
                    if frag is not None:
                        prof = frag.container_profile(containers=False)
                        bits += prof["bits"]
                        rows += prof["rows"]
        cache[fname] = (bits, rows)
        return bits, rows

    # -- flight-level CSE ---------------------------------------------------

    def _cse_pass(self, idx, calls, shards, results, unset):
        """Returns (shared subtrees materialized, consumer grafts beyond
        the first).  Occurrence collection and grafting are two passes:
        counting first over every candidate node, then grafting
        top-down so an occurrence nested inside an already-grafted
        subtree is never double-evaluated."""
        occurrences: dict[str, int] = {}
        roots: list[tuple[int, Call, Call | None]] = []
        for i, call in enumerate(calls):
            if results[i] is not unset:
                continue
            if call.name in _CSE_OPS:
                roots.append((i, call, None))
                self._collect(idx, call, occurrences)
            elif call.name == "Count" and len(call.children) == 1:
                child = call.children[0]
                if child.name in _CSE_OPS:
                    roots.append((i, child, call))
                    self._collect(idx, child, occurrences)
        shared_keys = {k for k, n in occurrences.items() if n >= 2}
        if not shared_keys:
            return 0, 0
        rows: dict[str, Any] = {}
        failed: set[str] = set()
        grafts = 0

        def materialize(key: str, node: Call):
            if key in rows:
                return rows[key]
            # Evaluate a CLONE: the consumer's own node gets grafted
            # over afterwards, and the evaluated tree must stay intact
            # for per-fragment version tracking in the cache layer.
            row = self.executor.cached_execute_call(
                idx, node.clone(), shards
            )
            rows[key] = row
            return row

        def graft(node: Call) -> Call | None:
            """Top-down: replace the HIGHEST shared node and do not
            descend into it; returns the replacement or None."""
            nonlocal grafts
            key = self._subtree_key(idx, node)
            if key in shared_keys and key not in failed:
                try:
                    row = materialize(key, node)
                except Exception:
                    # evaluation failure belongs to each consumer's own
                    # demux scope — leave every occurrence unplanned
                    failed.add(key)
                    return None
                grafts += 1
                return make_shared(row)
            for ci, c in enumerate(node.children):
                rep = graft(c)
                if rep is not None:
                    node.children[ci] = rep
            return None

        for i, root, parent in roots:
            rep = graft(root)
            if rep is None:
                continue
            if parent is not None:
                parent.children[0] = rep
            else:
                # whole top-level call shared: serve the slot directly,
                # copied like a cache hit so attrs/keys attach per query
                results[i] = rescache.copy_result(shared_row(rep))
        hits = max(0, grafts - len(rows)) if rows else 0
        self._count("cse_shared", len(rows))
        self._count("cse_hits", hits)
        return len(rows), hits

    def _collect(self, idx, node, occurrences) -> None:
        key = self._subtree_key(idx, node)
        if key is not None:
            occurrences[key] = occurrences.get(key, 0) + 1
        for c in node.children:
            if c.name in _CSE_OPS:
                self._collect(idx, c, occurrences)

    def _subtree_key(self, idx, node) -> str | None:
        if node.name not in _CSE_OPS:
            return None
        return rescache.subtree_key(idx, node)
