"""Query result types (reference: row.go Row, pilosa.go Pair/ValCount/
GroupCount/RowIdentifiers and internal/public.proto QueryResult union).

``Row`` is the cross-shard bitmap result: one device word-vector per shard
(the analogue of the reference's ordered rowSegments, row.go:332-344). Set
algebra stays on device; column ids materialize on host only at the API
edge (row.go Columns)."""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import numpy as np

import jax.numpy as jnp

from pilosa_tpu.ops import bitops


class Row:
    """Cross-shard bitmap result."""

    def __init__(self, segments: dict[int, Any] | None = None, n_words: int | None = None):
        # shard -> uint32[W] device array
        self.segments: dict[int, Any] = segments or {}
        self.n_words = n_words
        self.attrs: dict[str, Any] = {}
        self.keys: list[str] | None = None

    def _words(self, shard: int, like) -> Any:
        seg = self.segments.get(shard)
        if seg is None:
            if isinstance(like, np.ndarray):
                return np.zeros_like(like)
            return jnp.zeros_like(like)
        return seg

    def shards(self) -> list[int]:
        return sorted(self.segments)

    # -- set algebra (reference row.go:107-239) -----------------------------

    def intersect(self, other: "Row") -> "Row":
        out = {}
        for shard in set(self.segments) & set(other.segments):
            out[shard] = self.segments[shard] & other.segments[shard]
        return Row(out, self.n_words or other.n_words)

    def union(self, other: "Row") -> "Row":
        out = dict(self.segments)
        for shard, seg in other.segments.items():
            out[shard] = (out[shard] | seg) if shard in out else seg
        return Row(out, self.n_words or other.n_words)

    def difference(self, other: "Row") -> "Row":
        out = {}
        for shard, seg in self.segments.items():
            o = other.segments.get(shard)
            out[shard] = seg if o is None else seg & ~o
        return Row(out, self.n_words or other.n_words)

    def xor(self, other: "Row") -> "Row":
        out = dict(self.segments)
        for shard, seg in other.segments.items():
            out[shard] = (out[shard] ^ seg) if shard in out else seg
        return Row(out, self.n_words or other.n_words)

    def shift(self, n: int = 1) -> "Row":
        """Per-shard shift (no cross-shard carry, matching the reference's
        per-shard Shift semantics, roaring.go:944)."""
        out = {
            shard: (
                bitops.shift_row_host(seg, n)
                if isinstance(seg, np.ndarray)
                else bitops.shift_row(seg, n)
            )
            for shard, seg in self.segments.items()
        }
        return Row(out, self.n_words)

    # -- materialization ----------------------------------------------------
    #
    # Segments are either device arrays (throughput-tier results) or
    # host numpy arrays (latency-tier results served from the fragment
    # mirrors); counts dispatch per segment so a host-tier Row never
    # pays a device round trip.

    @staticmethod
    def _seg_count(seg) -> int:
        if isinstance(seg, np.ndarray):
            return bitops.popcount_host(seg)
        return int(bitops.count_bits(seg))

    def count(self) -> int:
        """Python-int exact total (per-shard int32 partials summed host
        side, so >2^31 totals are safe)."""
        return sum(self._seg_count(seg) for seg in self.segments.values())

    def intersection_count(self, other: "Row") -> int:
        total = 0
        for shard in set(self.segments) & set(other.segments):
            a, b = self.segments[shard], other.segments[shard]
            if isinstance(a, np.ndarray) and isinstance(b, np.ndarray):
                total += bitops.pair_count_host(a, b, "intersect")
            else:
                total += int(bitops.intersection_count(a, b))
        return total

    def is_empty(self) -> bool:
        return all(self._seg_count(s) == 0 for s in self.segments.values())

    def columns(self) -> np.ndarray:
        """Absolute sorted column ids (host materialization at the API
        edge)."""
        parts = []
        for shard in self.shards():
            words = np.asarray(self.segments[shard])
            width = len(words) * 32
            offs = bitops.unpack_columns(words)
            parts.append(offs + np.uint64(shard) * np.uint64(width))
        if not parts:
            return np.array([], dtype=np.uint64)
        return np.concatenate(parts)

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"attrs": self.attrs}
        if self.keys is not None:
            d["keys"] = self.keys
        else:
            d["columns"] = [int(c) for c in self.columns()]
        return d


@dataclass
class ValCount:
    """Sum/Min/Max result (reference pilosa.go ValCount)."""

    value: int = 0
    count: int = 0

    def to_dict(self) -> dict:
        return {"value": self.value, "count": self.count}


@dataclass
class Pair:
    """TopN entry (reference pilosa.go Pair)."""

    id: int = 0
    key: str | None = None
    count: int = 0

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"count": self.count}
        if self.key is not None:
            d["key"] = self.key
        else:
            d["id"] = self.id
        return d


@dataclass
class RowIdentifiers:
    """Rows() result (reference pilosa.go RowIdentifiers)."""

    rows: list[int] = dc_field(default_factory=list)
    keys: list[str] | None = None

    def to_dict(self) -> dict:
        if self.keys is not None:
            return {"keys": self.keys}
        return {"rows": self.rows}


@dataclass
class FieldRow:
    field: str
    row_id: int = 0
    row_key: str | None = None

    def to_dict(self) -> dict:
        d: dict[str, Any] = {"field": self.field}
        if self.row_key is not None:
            d["rowKey"] = self.row_key
        else:
            d["rowID"] = self.row_id
        return d


@dataclass
class GroupCount:
    """GroupBy entry (reference pilosa.go GroupCount)."""

    group: list[FieldRow]
    count: int

    def to_dict(self) -> dict:
        return {"group": [g.to_dict() for g in self.group], "count": self.count}


def result_to_json(result: Any) -> Any:
    """Lower any executor result to JSON-encodable data (the HTTP layer's
    QueryResult union, reference internal/public.proto:72-82)."""
    if isinstance(result, (Row, ValCount, RowIdentifiers, GroupCount)):
        return result.to_dict()
    if isinstance(result, Pair):
        return result.to_dict()
    if isinstance(result, list):
        return [result_to_json(r) for r in result]
    if isinstance(result, (bool, int, str)) or result is None:
        return result
    if isinstance(result, np.integer):
        return int(result)
    raise TypeError(f"unencodable result type: {type(result)!r}")
