"""General PQL-AST -> one-launch compiler over serving field stacks.

The reference executes arbitrary bitmap trees per shard inside its worker
pool (executor.go:653-680 executeBitmapCallShard recursing over
Row/Intersect/Union/Difference/Xor/Not).  The TPU analogue traces the
SAME tree once into a single XLA program over the cached ``[S, R, W]``
field stacks (SURVEY §7: "PQL AST -> traced JAX computation, one XLA
program per query shape, cached"):

* The program is cached by the AST's *shape* — the operator tree plus
  which field each leaf reads — never by row ids.  Row ids arrive as an
  ``int32`` slots input, so ``Count(Intersect(Row(f=1), Row(f=2)))`` and
  ``Count(Intersect(Row(f=7), Row(f=9)))`` share one compiled program,
  and a batch of same-shape Counts runs as ONE launch via an on-device
  scan over the slot rows.
* Absent rows ride through as slot ``-1``: the leaf gathers row 0 and
  masks it to zero words, which is exactly the empty-row semantics of
  every operator (including Not/Difference).
* ``Not`` is rewritten at match time into
  ``Difference(Row(_exists=0), child)`` — the reference's executeNot
  (executor.go) against the existence field, as a plain tree node.
* A time-range ``Row(f=v, from=..., to=...)`` expands into a Union of
  per-view leaves over the minimal time-view cover (reference
  executor.go:1515-1531; the reference treats time views as ordinary
  fragments, view.go:33-38) — so time-quantum queries ride the same
  compiled one-launch programs, with one cached stack per (field, view).

Launches are counted in :data:`launches` so tests can assert O(1)
dispatch per query batch regardless of shard count or tree width.
"""

from __future__ import annotations

from functools import lru_cache
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from pilosa_tpu.core import timequantum
from pilosa_tpu.core.field import FIELD_TYPE_INT
from pilosa_tpu.core.view import VIEW_STANDARD
from pilosa_tpu.exec import planner as planner_mod
from pilosa_tpu.obs import devledger
from pilosa_tpu.pql.ast import Call, Condition

# Device cost ledger site for compiled-plan launches: every run_* call
# opens a launch window so XLA compiles (new AST shape or batch bucket)
# attribute here, and the compiled-callable identity feeds cache-hit
# accounting.
_DL = devledger.site("exec.astbatch")

# Device launches issued by compiled programs (tests assert O(1) per
# batch; one count-group launch answers every same-shape Count).
launches = 0

_OPS = {
    "Intersect": "intersect",
    "Union": "union",
    "Difference": "difference",
    "Xor": "xor",
}

# Largest time-view cover a range leaf may expand to: past this, the
# per-view stack builds and the unrolled leaf gathers cost more than the
# segment path's plain union (a fine quantum over a wide window can
# cover thousands of views).
MAX_TIME_COVER = 16

# sig nodes: ("row", stack_ordinal) | (op, *child_sigs).  Leaves refer
# to (field, view) stacks by first-appearance ORDINAL, not by name: the
# compiled program depends only on the tree shape and stack positions,
# so a rolling time window (same cover shape, different view names)
# reuses one program instead of tracing a fresh one per period.  The
# actual (field, view) pairs ride alongside in ``pairs`` and join the
# executor's launch-group key.


def _stackable_field(idx, fname: str):
    """The field when it can serve stacked reads at all (per-view
    existence is checked by the stack builder; an absent view is an
    all-zero leaf)."""
    if fname is None:
        return None
    field = idx.field(fname)
    if field is None or field.field_type == FIELD_TYPE_INT:
        return None
    return field


def _ordinal(pairs: list[tuple[str, str]], fname: str, vname: str) -> int:
    pair = (fname, vname)
    try:
        return pairs.index(pair)
    except ValueError:
        pairs.append(pair)
        return len(pairs) - 1


def match_tree(
    idx,
    call: Call,
    leaves: list[tuple[str, str, int]],
    pairs: list[tuple[str, str]],
):
    """``sig`` for a batchable bitmap tree, appending its
    (field, view, row) leaves in traversal order and the distinct
    (field, view) stack pairs to ``pairs`` (the compiled program's
    argument order); None when any node falls outside the compilable set
    (BSI conditions, Shift, keyed rows...)."""
    name = call.name
    if name == planner_mod.SHARED:
        # flight-planner graft (exec/planner.py): the subtree is already
        # a materialized host row.  Declining the compiled path here is
        # the POINT of the graft — the consumer combines it with cheap
        # host segment algebra instead of re-launching the whole tree.
        # (Any unknown name declines anyway; this spells the contract.)
        return None
    if name == "Row":
        fname = call.field_arg()
        field = _stackable_field(idx, fname)
        if field is None or call.children:
            return None
        v = call.args.get(fname)
        if not isinstance(v, int) or isinstance(v, bool):
            return None
        if "from" in call.args or "to" in call.args:
            # time range -> Union over the minimal view cover
            if set(call.args) - {fname, "from", "to"}:
                return None
            try:
                cover = timequantum.view_cover(
                    field, call.args.get("from"), call.args.get("to"),
                    VIEW_STANDARD,
                )
            except ValueError:
                return None
            if not cover or len(cover) > MAX_TIME_COVER:
                # empty range (segment path is free) or a cover so wide
                # that unrolled leaves/stacks would cost more than the
                # per-fragment union
                return None
            for vname in cover:
                leaves.append((fname, vname, v))
            return (
                "union",
                *[("row", _ordinal(pairs, fname, vn)) for vn in cover],
            )
        if set(call.args) != {fname}:
            return None
        if field.view(VIEW_STANDARD) is None:
            return None
        leaves.append((fname, VIEW_STANDARD, v))
        return ("row", _ordinal(pairs, fname, VIEW_STANDARD))
    if name == "Not":
        # executeNot: exists-row difference (requires track_existence)
        if len(call.children) != 1 or call.args or not idx.track_existence:
            return None
        ef = idx.existence_field()
        if ef is None or ef.view(VIEW_STANDARD) is None:
            return None
        leaves.append((ef.name, VIEW_STANDARD, 0))
        esig = ("row", _ordinal(pairs, ef.name, VIEW_STANDARD))
        child = match_tree(idx, call.children[0], leaves, pairs)
        if child is None:
            return None
        return ("difference", esig, child)
    op = _OPS.get(name)
    if op is not None:
        if not call.children or call.args:
            return None
        subs = []
        for c in call.children:
            s = match_tree(idx, c, leaves, pairs)
            if s is None:
                return None
            subs.append(s)
        return (op, *subs)
    return None


def match_count(
    idx,
    call: Call,
    leaves: list[tuple[str, str, int]],
    pairs: list[tuple[str, str]],
):
    """sig for ``Count(tree)`` when the tree is compilable and not a bare
    Row (plain row counts are already one gather on the segment path)."""
    if call.name != "Count" or len(call.children) != 1 or call.args:
        return None
    child = call.children[0]
    if child.name == "Row":
        return None
    return match_tree(idx, child, leaves, pairs)


def _build(sig, ctr: list[int]):
    """Recursively build the tree evaluator: (stacks, slots) -> [S, W]
    words.  Leaf order mirrors match_tree's traversal order."""
    if sig[0] == "row":
        li = ctr[0]
        ctr[0] += 1
        fi = sig[1]

        def leaf(stacks, slots, li=li, fi=fi):
            s = slots[li]
            row = stacks[fi][:, jnp.maximum(s, 0)]  # [S, W]
            return row & jnp.where(
                s >= 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0)
            )

        return leaf
    op = sig[0]
    kids = [_build(k, ctr) for k in sig[1:]]

    if op == "difference":
        if len(kids) == 1:
            return kids[0]

        # left fold a\b\c == a & ~(b | c) (reference row.go Difference)
        def node(stacks, slots):
            rest = kids[1](stacks, slots)
            for k in kids[2:]:
                rest = rest | k(stacks, slots)
            return kids[0](stacks, slots) & ~rest

        return node

    fold = {"intersect": lambda a, b: a & b, "union": lambda a, b: a | b,
            "xor": lambda a, b: a ^ b}[op]

    def node(stacks, slots):
        out = kids[0](stacks, slots)
        for k in kids[1:]:
            out = fold(out, k(stacks, slots))
        return out

    return node


def _count_scan(root, stacks, slots_b):
    """int32 [B, S] per-shard counts for a slot batch: on-device scan
    over the batch, no [B, S, W] materialization.  Shared by the local
    and spanning compiled programs so count semantics cannot diverge."""

    def body(_, sl):
        words = root(stacks, sl)
        return None, jnp.sum(
            lax.population_count(words).astype(jnp.int32), axis=-1
        )

    _, counts = lax.scan(body, None, slots_b)
    return counts


@lru_cache(maxsize=256)
def compiled(sig, count_mode: bool):
    """(jitted_fn, n_leaves) for an AST shape.  ``count_mode`` programs
    take ``(stacks, slots[B, L])`` and return int32 ``[B, S]`` per-shard
    counts (scan over the batch — no [B, S, W] materialization); bitmap
    programs take ``(stacks, slots[L])`` and return the uint32 ``[S, W]``
    result words."""
    ctr = [0]
    root = _build(sig, ctr)
    n_leaves = ctr[0]

    if count_mode:

        @jax.jit
        def run(stacks, slots_b):
            return _count_scan(root, stacks, slots_b)  # [B, S]

    else:

        @jax.jit
        def run(stacks, slots):
            return root(stacks, slots)  # [S, W]

    return run, n_leaves


@lru_cache(maxsize=256)
def _compiled_spanning(sig, mesh, axis, chunk, n_stacks):
    """jit(shard_map) count-batch program for a PROCESS-SPANNING mesh:
    per-shard partials are not host addressable there, so each device
    evaluates the tree over its local shard block in ``chunk``-shard
    slices and the reduce is an in-program chunked psum with (hi, lo)
    uint32 carry-save (exact past int32 — the same machinery as
    ops/kernels.py's spanning pair/gram kinds).  Returns replicated
    (hi, lo) uint32[B] arrays."""
    from jax.sharding import PartitionSpec as P

    from pilosa_tpu.compat import shard_map

    from pilosa_tpu.ops import kernels as _k

    ctr = [0]
    root = _build(sig, ctr)
    n_leaves = ctr[0]

    def local(*args):
        *stks, slots_b = args

        def part(*blks):
            # [B, S_chunk] -> [B] int32, chunk-bounded by construction
            return _count_scan(root, tuple(blks), slots_b).sum(axis=1)

        return _k._carry_psum_chunks(part, tuple(stks), axis, chunk)

    in_specs = tuple(P(axis, None, None) for _ in range(n_stacks)) + (
        P(None),
    )
    fn = jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(None), P(None)),
            check_vma=False,
        )
    )
    return fn, n_leaves


def run_count_batch(sig, stacks: tuple, slots_np: np.ndarray) -> np.ndarray:
    """One launch: int64 totals for a batch of same-shape Counts.
    ``slots_np`` is int32 [B, L] (pad rows with -1 slots are fine — they
    count zero and callers slice them off).  Local stacks sum [B, S]
    partials host-side; process-spanning stacks reduce in-program and
    raise ValueError only when totals could exceed int32 even per
    single-shard psum slice (the row_counts contract)."""
    global launches
    from pilosa_tpu.ops import kernels as _k

    m = _k.shards_axis_of(stacks[0])
    if m is not None and _k.mesh_spans_processes(m[0]):
        mesh, axis = m
        W = stacks[0].shape[2]
        chunk = _k._psum_chunk_size(mesh, W)
        if chunk < 1:
            raise ValueError(
                "AST count totals exceed int32 even per single psum"
                " slice; shrink the shard width or the per-host mesh"
            )
        fn, n_leaves = _compiled_spanning(
            sig, mesh, axis, chunk, len(stacks)
        )
        assert slots_np.shape[1] == n_leaves
        launches += 1
        label = f"count_span B{slots_np.shape[0]} S{stacks[0].shape[0]}"
        _DL.track(fn, (slots_np.shape, stacks[0].shape))
        with _DL.launch(sig=label):
            hi, lo = fn(*stacks, jnp.asarray(slots_np))
        return _k._hi_lo_total(hi, lo)
    fn, n_leaves = compiled(sig, True)
    assert slots_np.shape[1] == n_leaves
    launches += 1
    label = f"count B{slots_np.shape[0]} S{stacks[0].shape[0]}"
    _DL.track(fn, (slots_np.shape, tuple(s.shape for s in stacks)))
    with _DL.launch(sig=label) as w:
        partials = np.asarray(
            fn(stacks, jnp.asarray(slots_np))
        ).astype(np.int64)
    if w.compiles:
        devledger.ledger().analyze_cost(
            _DL, fn, stacks, jnp.asarray(slots_np), sig=label
        )
    return partials.sum(axis=1)


def run_bitmap(sig, stacks: tuple, slots_np: np.ndarray):
    """One launch: the uint32 [S, W] result words of a bitmap tree."""
    global launches
    fn, n_leaves = compiled(sig, False)
    assert slots_np.shape[0] == n_leaves
    launches += 1
    _DL.track(fn, tuple(s.shape for s in stacks))
    with _DL.launch(sig=f"bitmap S{stacks[0].shape[0]}"):
        return fn(stacks, jnp.asarray(slots_np))


# ------------------------------------------------------------- BSI signing
#
# BSI op classes the executor's cross-request batch lane understands
# (executor._batch_bsi).  A signed call joins a (field, depth, op-class)
# flight group and is answered by ONE shared slice-plane launch per group
# (ops/bsi.py batched kernels).  The dispatch-parity graftlint pass
# (part C) checks this class list against the executor's handlers, so a
# class signed here but never grouped there is a CI failure.

BSI_RANGE = "bsi.range"
BSI_RANGE_COUNT = "bsi.range_count"
BSI_SUM = "bsi.sum"
BSI_MIN = "bsi.min"
BSI_MAX = "bsi.max"
BSI_GROUPBY = "bsi.groupby"

BSI_OP_CLASSES = (
    BSI_RANGE, BSI_RANGE_COUNT, BSI_SUM, BSI_MIN, BSI_MAX, BSI_GROUPBY,
)


def _bsi_condition(idx, call: Call):
    """(field, Condition) when ``call`` is a pure BSI range predicate —
    ``Row(v < 3)`` / ``Range(v < 3)`` over an int field; None otherwise.
    ``== null`` is left unsigned so the per-call path raises it inside
    the owning query's demux scope."""
    if call.name not in ("Row", "Range") or call.children:
        return None
    fname = call.field_arg()
    if fname is None or set(call.args) != {fname}:
        return None
    field = idx.field(fname)
    if field is None or not field.is_bsi():
        return None
    cond = call.args.get(fname)
    if not isinstance(cond, Condition):
        return None
    if cond.op == "==" and cond.value is None:
        return None
    return field, cond


def match_bsi(idx, call: Call):
    """Sign one call as BSI-batchable: ``(op_class, field, condition)``
    (condition None for the aggregate classes, which carry their filter
    as a child/arg instead) or None.  Conservative by construction —
    anything unsigned keeps the exact per-call semantics."""
    name = call.name
    m = _bsi_condition(idx, call)
    if m is not None:
        return BSI_RANGE, m[0], m[1]
    if name == "Count" and len(call.children) == 1 and not call.args:
        m = _bsi_condition(idx, call.children[0])
        if m is not None:
            return BSI_RANGE_COUNT, m[0], m[1]
        return None
    if name in ("Sum", "Min", "Max"):
        fname, ok = call.string_arg("field")
        if not ok:
            fname = call.args.get("_field")
        field = idx.field(fname) if fname else None
        if field is None or not field.is_bsi():
            return None
        cls = {"Sum": BSI_SUM, "Min": BSI_MIN, "Max": BSI_MAX}[name]
        return cls, field, None
    if name == "GroupBy":
        filt, has = call.call_arg("filter")
        if has and filt is not None:
            m = _bsi_condition(idx, filt)
            if m is not None:
                return BSI_GROUPBY, m[0], m[1]
    return None
