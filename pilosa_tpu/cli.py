"""Command-line interface (reference: cmd/ cobra tree + ctl/ subcommands).

    pilosa-tpu server            run a node (reference ctl/server)
    pilosa-tpu import            CSV/value import into a running node
    pilosa-tpu export            CSV export from a running node
    pilosa-tpu check             offline integrity check of fragment files
                                 (reference ctl/check.go:47-133)
    pilosa-tpu inspect           print container stats of fragment files
                                 (reference ctl/inspect.go)
    pilosa-tpu generate-config   emit default config
                                 (reference ctl/generate_config.go)

Config precedence mirrors the reference (cmd/root.go): flags > env
(PILOSA_TPU_*) > config file (JSON or TOML) > defaults.
"""

from __future__ import annotations

# graftlint: disable-file=log-discipline -- CLI subcommands: stdout IS the
# user interface (CSV export, inspect tables, config emission)

import argparse
import json
import os
import sys
import urllib.request

DEFAULT_CONFIG = {
    "data-dir": "~/.pilosa-tpu",
    "bind": "localhost:10101",
    "long-query-time": 0.0,
    # null = auto (80% of the accelerator's bytes_limit on TPU, unlimited
    # accounting on CPU — core/membudget.py); 0 = force unlimited
    # accounting; >0 = explicit cap in bytes
    "hbm-budget-bytes": None,
    "cluster": {"replicas": 1, "coordinator": True, "hosts": []},
    # reference api.go:66-96 importWorkerPoolSize (default 2)
    "import": {"workers": 2, "queue-depth": 16},
    "anti-entropy": {"interval": 600},
    # reference server/config.go:160 MaxWritesPerRequest (0 disables)
    "max-writes-per-request": 5000,
    "metric": {"service": "none", "poll-interval": 60, "diagnostics-sink": ""},
    "tracing": {"enabled": False},
    # crash-durable diagnostics spool under <data-dir>/_blackbox/
    # (obs/blackbox.py); postmortems served at GET /debug/postmortem
    "blackbox": {
        "enabled": True,
        "interval": 5.0,
        "max-segments": 64,
        "max-bytes": 16 << 20,
        "keep-postmortems": 4,
        "history-window": 60.0,
    },
}


def _load_config(path: str | None) -> dict:
    cfg = json.loads(json.dumps(DEFAULT_CONFIG))  # deep copy
    if path:
        with open(path, "rb") as f:
            if path.endswith(".toml"):
                import tomllib

                file_cfg = tomllib.load(f)
            else:
                file_cfg = json.load(f)
        _deep_update(cfg, file_cfg)
    env_map = {
        "PILOSA_TPU_DATA_DIR": ("data-dir",),
        "PILOSA_TPU_BIND": ("bind",),
        "PILOSA_TPU_LONG_QUERY_TIME": ("long-query-time",),
        "PILOSA_TPU_HBM_BUDGET_BYTES": ("hbm-budget-bytes",),
    }
    for env, keys in env_map.items():
        if env in os.environ:
            d = cfg
            for k in keys[:-1]:
                d = d[k]
            d[keys[-1]] = os.environ[env]
    return cfg


def _deep_update(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_update(dst[k], v)
        else:
            dst[k] = v


def _ensure_backend() -> None:
    """Fall back to the CPU backend when the accelerator can't initialize
    (e.g. another process holds the chip grant) — a degraded node beats a
    node whose every query 500s."""
    import jax

    from pilosa_tpu.platform import honor_platform_env

    honor_platform_env()
    try:
        jax.devices()
    except Exception as e:
        print(f"warning: accelerator unavailable ({e}); using CPU backend")
        jax.config.update("jax_platforms", "cpu")
        jax.devices()


def _parse_statsd_host(raw: str) -> tuple[str, int]:
    """(host, port) from a statsd ``host`` config value.  Accepts
    "host:8125", "host" (default port), "[::1]:8125", "[::1]", and a
    bare IPv6 literal "::1" (which a naive rpartition would mangle
    into host ":" port 1)."""
    if raw.startswith("["):
        host, _, rest = raw[1:].partition("]")
        port = rest[1:] if rest.startswith(":") else "8125"
    elif raw.count(":") == 1:
        host, _, port = raw.partition(":")
    else:
        host, port = raw, "8125"
    if not port.isdigit():
        port = "8125"
    return host or "127.0.0.1", int(port)


def cmd_server(args) -> int:
    _ensure_backend()
    from pilosa_tpu.obs.stats import MemStatsClient, NOP
    from pilosa_tpu.server.node import NodeServer

    cfg = _load_config(args.config)
    data_dir = os.path.expanduser(args.data_dir or cfg["data-dir"])
    bind = args.bind or cfg["bind"]
    host, _, port = bind.rpartition(":")
    host = host or "localhost"

    # HBM budget precedence: flag > env/config > auto-probe at first use
    # (membudget.default_budget).  Explicit 0 on ANY channel forces
    # unlimited accounting; absence means auto.
    from pilosa_tpu.core import membudget

    hbm = args.hbm_budget
    if hbm is None:
        raw = cfg.get("hbm-budget-bytes")
        hbm = int(raw) if raw is not None else None
    if hbm is not None:
        membudget.configure(hbm or None)

    # metric.service selects the backend (reference server.go:397-411):
    # none | expvar/prometheus (in-memory, served at /metrics and
    # /debug/vars) | statsd/datadog (UDP push, reference
    # statsd/statsd.go:48).
    metric_cfg = cfg.get("metric", {})
    service = metric_cfg.get("service", "none")
    if service == "none":
        stats_client = NOP
    elif service in ("statsd", "datadog"):
        from pilosa_tpu.obs.stats import StatsDClient

        mhost, mport = _parse_statsd_host(
            metric_cfg.get("host", "127.0.0.1:8125")
        )
        stats_client = StatsDClient(mhost, mport)
    else:  # expvar / prometheus: in-memory client served over HTTP
        stats_client = MemStatsClient()
    tls_cfg = cfg.get("tls", {})
    node = NodeServer(
        data_dir=data_dir,
        host=host,
        port=int(port),
        replica_n=int(cfg.get("cluster", {}).get("replicas", 1)),
        long_query_time=float(cfg["long-query-time"]),
        stats_client=stats_client,
        metric_poll_interval=float(metric_cfg.get("poll-interval", 10) or 10),
        tls_cert=args.tls_cert or tls_cfg.get("certificate") or None,
        tls_key=args.tls_key or tls_cfg.get("key") or None,
        tls_skip_verify=bool(tls_cfg.get("skip-verify", False)),
        tls_ca_cert=getattr(args, "tls_ca_cert", None)
        or tls_cfg.get("ca-certificate")
        or None,
        import_workers=int(cfg.get("import", {}).get("workers", 2)),
        max_writes_per_request=int(cfg.get("max-writes-per-request", 5000)),
        import_queue_depth=int(cfg.get("import", {}).get("queue-depth", 16)),
        blackbox_enabled=bool(cfg.get("blackbox", {}).get("enabled", True)),
        blackbox_interval=float(cfg.get("blackbox", {}).get("interval", 5.0)),
        blackbox_max_segments=int(
            cfg.get("blackbox", {}).get("max-segments", 64)
        ),
        blackbox_max_bytes=int(
            cfg.get("blackbox", {}).get("max-bytes", 16 << 20)
        ),
        blackbox_keep_postmortems=int(
            cfg.get("blackbox", {}).get("keep-postmortems", 4)
        ),
        blackbox_history_window=float(
            cfg.get("blackbox", {}).get("history-window", 60.0)
        ),
    )
    if node.postmortem is not None:
        pm = node.postmortem
        print(
            f"previous life died dirty: postmortem {pm['id']} "
            f"(crash loop {pm['crashLoop']}) at /debug/postmortem"
        )
    # SIGTERM drains the node and exits 0 — an orderly stop must never
    # read as a crash on the next boot
    node.install_signal_handlers()
    # tracing exporter + sampler (reference tracing config
    # server/config.go:139-145)
    trace_cfg = cfg.get("tracing", {})
    if trace_cfg.get("endpoint"):
        from pilosa_tpu.obs.export import OTLPSpanExporter
        from pilosa_tpu.obs.tracing import ExportingTracer, set_tracer

        set_tracer(
            ExportingTracer(
                OTLPSpanExporter(trace_cfg["endpoint"]),
                sample_rate=float(trace_cfg.get("sampler-param", 1.0)),
            )
        )
    # Periodic diagnostics flushes need somewhere to go (the reference
    # phones home; here a local JSONL sink). Without a sink the
    # /internal/diagnostics route serves snapshots on demand instead.
    diag_sink = metric_cfg.get("diagnostics-sink")
    if diag_sink:
        node.diagnostics.sink_path = os.path.expanduser(diag_sink)
        node.diagnostics.start(float(metric_cfg.get("poll-interval", 60) or 60))
    node.start()
    # periodic replica repair + translate-log replication (reference
    # server.go:494-546 monitorAntiEntropy; 0 disables)
    node.start_anti_entropy(
        float(cfg.get("anti-entropy", {}).get("interval", 600) or 0)
    )
    print(f"pilosa-tpu server listening on {node.uri}, data dir {data_dir}")
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        node.stop()
    return 0


def _http(args, method: str, path: str, body: bytes | None = None, content_type="application/json"):
    url = f"http://{args.host}{path}"
    req = urllib.request.Request(url, data=body, method=method)
    req.add_header("Content-Type", content_type)
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.read()


def cmd_import(args) -> int:
    """CSV import (reference ctl/import.go:82-378): lines of row,col or
    col,value with --field-type int."""
    rows, cols, values, timestamps = [], [], [], []
    has_ts = False
    for path in args.files:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                parts = line.split(",")
                if args.int_values:
                    cols.append(int(parts[0]))
                    values.append(int(parts[1]))
                else:
                    rows.append(parts[0] if args.row_keys else int(parts[0]))
                    cols.append(parts[1] if args.col_keys else int(parts[1]))
                    if len(parts) > 2:
                        has_ts = True
                        timestamps.append(parts[2])
                    else:
                        timestamps.append(None)
    if args.int_values:
        payload = {"columnIDs": cols, "values": values}
    else:
        payload = {
            ("rowKeys" if args.row_keys else "rowIDs"): rows,
            ("columnKeys" if args.col_keys else "columnIDs"): cols,
        }
        if has_ts:
            payload["timestamps"] = timestamps
    if args.clear:
        payload["clear"] = True
    _http(
        args,
        "POST",
        f"/index/{args.index}/field/{args.field}/import",
        json.dumps(payload).encode(),
    )
    total = len(cols)
    print(f"imported {total} records into {args.index}/{args.field}")
    return 0


def cmd_export(args) -> int:
    data = _http(args, "GET", f"/export?index={args.index}&field={args.field}")
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    out.write(data.decode())
    if out is not sys.stdout:
        out.close()
    return 0


def cmd_check(args) -> int:
    """Offline integrity check of roaring fragment files (reference
    ctl/check.go:47-133)."""
    from pilosa_tpu.storage import roaring

    failed = 0
    for path in args.files:
        try:
            with open(path, "rb") as f:
                positions = roaring.deserialize(f.read())
            print(f"{path}: OK ({len(positions)} bits)")
        except Exception as e:
            print(f"{path}: FAILED: {e}")
            failed += 1
    return 1 if failed else 0


def cmd_inspect(args) -> int:
    """Container statistics of a fragment file (reference ctl/inspect.go)."""
    import numpy as np

    from pilosa_tpu.storage import roaring

    for path in args.files:
        with open(path, "rb") as f:
            data = f.read()
        positions = roaring.deserialize(data)
        keys = positions >> np.uint64(16) if len(positions) else positions
        n_containers = len(np.unique(keys)) if len(positions) else 0
        print(f"{path}:")
        print(f"  bits: {len(positions)}")
        print(f"  containers: {n_containers}")
        if len(positions):
            print(f"  min position: {positions.min()}")
            print(f"  max position: {positions.max()}")
    return 0


def _cluster_hosts(args) -> tuple[list[str], str]:
    """([host:port of every live node], primary's host:port) — backup
    must see EVERY node's fragments and the translation PRIMARY's log
    (a replica's copy can lag by one anti-entropy interval)."""
    try:
        nodes = json.loads(_http(args, "GET", "/internal/nodes"))
    except Exception:
        return [args.host], args.host
    hosts, primary = [], args.host
    for n in nodes:
        uri = n.get("uri", "")
        host = uri.split("://", 1)[-1] if uri else ""
        if not host:
            continue
        hosts.append(host)
        if n.get("isCoordinator"):
            primary = host
    return hosts or [args.host], primary


def cmd_backup(args) -> int:
    """Online backup of a running node/cluster into one tar (reference
    fragment.go:2424-2594's tar fragment format, operator-facing like
    ctl backup): schema.json + translate.json + every fragment as a
    roaring blob at fragments/<index>/<field>/<view>/<shard>.roaring.
    The fragment inventory is the union over EVERY cluster node (each
    node reports only its local fragments) and each blob is fetched
    from a node that holds it; the translation feed comes from the
    primary.  Row/column attributes are not included."""
    import argparse as _argparse
    import io
    import tarfile

    def add(tar, name: str, data: bytes) -> None:
        info = tarfile.TarInfo(name)
        info.size = len(data)
        tar.addfile(info, io.BytesIO(data))

    hosts, primary_host = _cluster_hosts(args)
    schema = _http(args, "GET", "/schema")
    # union inventory; remember one holder per fragment
    holder_of: dict[tuple, str] = {}
    for host in hosts:
        hargs = _argparse.Namespace(host=host)
        inv = json.loads(_http(hargs, "GET", "/internal/fragments"))[
            "fragments"
        ]
        for f in inv:
            if args.index and f["index"] != args.index:
                continue
            holder_of.setdefault(
                (f["index"], f["field"], f["view"], f["shard"]), host
            )
    # full translation feed from the PRIMARY (pull in pages)
    pargs = _argparse.Namespace(host=primary_host)
    entries, offset = [], 0
    while True:
        page = json.loads(
            _http(pargs, "GET", f"/internal/translate/log?offset={offset}")
        )
        entries.extend(page["entries"])
        if page["offset"] == offset:
            break
        offset = page["offset"]
    if args.index:
        # column keys live under the index name; row keys under the
        # same index with a field name — both carry entry[0] == index
        entries = [e for e in entries if e[0] == args.index]
    out = sys.stdout.buffer if args.output == "-" else open(args.output, "wb")
    with tarfile.open(fileobj=out, mode="w|") as tar:
        add(tar, "schema.json", schema)
        add(tar, "translate.json", json.dumps({"entries": entries}).encode())
        for (index, field, view, shard), host in sorted(holder_of.items()):
            blob = _http(
                _argparse.Namespace(host=host),
                "GET",
                f"/internal/fragment/data?index={index}&field={field}"
                f"&view={view}&shard={shard}",
            )
            add(
                tar,
                f"fragments/{index}/{field}/{view}/{shard}.roaring",
                blob,
            )
    if out is not sys.stdout.buffer:
        out.close()
    print(
        f"backed up {len(holder_of)} fragments, {len(entries)} key mappings",
        file=sys.stderr,
    )
    return 0


def cmd_restore(args) -> int:
    """Restore a backup tar into a running node/cluster: apply schema,
    install key translations, then import-roaring every fragment (the
    import path routes each shard to its owners, so restoring into a
    different cluster shape re-places the data)."""
    import tarfile

    src = sys.stdin.buffer if args.file == "-" else open(args.file, "rb")
    n_frags = 0
    with tarfile.open(fileobj=src, mode="r|*") as tar:
        for member in tar:
            f = tar.extractfile(member)
            if f is None:
                continue
            data = f.read()
            if member.name == "schema.json":
                # /schema applies locally (the resize path uses it
                # per-node), so install it on EVERY node before any
                # fragment import forwards to a replica
                import argparse as _argparse

                hosts, _ = _cluster_hosts(args)
                for host in hosts:
                    _http(
                        _argparse.Namespace(host=host),
                        "POST",
                        "/schema",
                        data,
                    )
            elif member.name == "translate.json":
                _http(
                    args, "POST", "/internal/translate/restore", data
                )
            elif member.name.startswith("fragments/"):
                _, index, field, view, fname = member.name.split("/")
                shard = int(fname.removesuffix(".roaring"))
                _http(
                    args,
                    "POST",
                    f"/index/{index}/field/{field}/import-roaring/{shard}"
                    f"?view={view}",
                    data,
                    content_type="application/octet-stream",
                )
                n_frags += 1
    if src is not sys.stdin.buffer:
        src.close()
    print(f"restored {n_frags} fragments", file=sys.stderr)
    return 0


def cmd_generate_config(args) -> int:
    print(json.dumps(DEFAULT_CONFIG, indent=2))
    return 0


def cmd_config(args) -> int:
    """Print the effective configuration after file + env merging
    (reference `pilosa config`, ctl/config.go)."""
    print(json.dumps(_load_config(args.config), indent=2))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="pilosa-tpu", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("server", help="run a pilosa-tpu node")
    ps.add_argument("-d", "--data-dir", default=None)
    ps.add_argument("-b", "--bind", default=None)
    ps.add_argument("-c", "--config", default=None)
    ps.add_argument(
        "--hbm-budget",
        type=int,
        default=None,
        help="HBM budget in bytes for device-resident fragment/stack "
        "copies (default: 80%% of the accelerator's memory limit)",
    )
    ps.add_argument("--tls-cert", default=None, help="TLS certificate path (enables HTTPS)")
    ps.add_argument("--tls-key", default=None, help="TLS private key path")
    ps.add_argument(
        "--tls-ca-cert",
        default=None,
        help="CA bundle for verifying intra-cluster certs (private CA)",
    )
    ps.set_defaults(fn=cmd_server)

    for name, fn in [("import", cmd_import)]:
        pi = sub.add_parser(name, help="bulk import CSV")
        pi.add_argument("--host", default="localhost:10101")
        pi.add_argument("-i", "--index", required=True)
        pi.add_argument("-f", "--field", required=True)
        pi.add_argument("--int-values", action="store_true", help="col,value CSV for int fields")
        pi.add_argument("--row-keys", action="store_true")
        pi.add_argument("--col-keys", action="store_true")
        pi.add_argument("--clear", action="store_true")
        pi.add_argument("files", nargs="+")
        pi.set_defaults(fn=fn)

    pe = sub.add_parser("export", help="export a field as CSV")
    pe.add_argument("--host", default="localhost:10101")
    pe.add_argument("-i", "--index", required=True)
    pe.add_argument("-f", "--field", required=True)
    pe.add_argument("-o", "--output", default="-")
    pe.set_defaults(fn=cmd_export)

    pb = sub.add_parser("backup", help="backup a running cluster to a tar")
    pb.add_argument("--host", default="localhost:10101")
    pb.add_argument("-o", "--output", default="-")
    pb.add_argument("-i", "--index", default=None, help="only this index")
    pb.set_defaults(fn=cmd_backup)

    pr = sub.add_parser("restore", help="restore a backup tar into a cluster")
    pr.add_argument("--host", default="localhost:10101")
    pr.add_argument("file", help="backup tar path, or - for stdin")
    pr.set_defaults(fn=cmd_restore)

    pc = sub.add_parser("check", help="verify fragment files")
    pc.add_argument("files", nargs="+")
    pc.set_defaults(fn=cmd_check)

    pn = sub.add_parser("inspect", help="inspect fragment files")
    pn.add_argument("files", nargs="+")
    pn.set_defaults(fn=cmd_inspect)

    pg = sub.add_parser("generate-config", help="print default config")
    pg.set_defaults(fn=cmd_generate_config)

    pcfg = sub.add_parser(
        "config", help="print the effective config (file + env merged)"
    )
    pcfg.add_argument("-c", "--config", default=None)
    pcfg.set_defaults(fn=cmd_config)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
