"""Shard-width configuration.

The unit of horizontal distribution is the *shard*: a contiguous block of
``SHARD_WIDTH`` columns. Mirrors the reference's build-time shard width
(reference: fragment.go:50-53, shardwidth/16.go..32.go, Makefile:9
``SHARD_WIDTH=20``) but selected at process start via the environment
variable ``PILOSA_TPU_SHARD_WIDTH`` (exponent, default 20).

On TPU a shard's column axis becomes the lane dimension of dense bitmap
tensors: ``SHARD_WIDTH // 32`` uint32 words per row. Widths are restricted
to >= 2^12 so the word count stays a multiple of 128 (TPU lane tiling).
"""

from __future__ import annotations

import os

WORD_BITS = 32

_DEFAULT_EXP = 20

SHARD_WIDTH_EXP: int = int(os.environ.get("PILOSA_TPU_SHARD_WIDTH", str(_DEFAULT_EXP)))
if not 12 <= SHARD_WIDTH_EXP <= 32:
    raise ValueError(
        f"PILOSA_TPU_SHARD_WIDTH must be in [12, 32], got {SHARD_WIDTH_EXP}"
    )

#: Number of columns per shard.
SHARD_WIDTH: int = 1 << SHARD_WIDTH_EXP

#: Number of uint32 words in one row of one shard's bitmap tensor.
SHARD_WORDS: int = SHARD_WIDTH // WORD_BITS


def shard_of(col: int) -> int:
    """Shard that owns an absolute column id (reference: fragment.go:3077)."""
    return col >> SHARD_WIDTH_EXP


def col_in_shard(col: int) -> int:
    """Column offset within its shard."""
    return col & (SHARD_WIDTH - 1)


def word_of(col_offset: int) -> int:
    """Word index of a column offset within a row's word array."""
    return col_offset >> 5


def bit_of(col_offset: int) -> int:
    """Bit index of a column offset within its word (little-endian)."""
    return col_offset & 31
