"""Nodes and persisted topology (reference: cluster.go Node/Topology,
.topology file cluster.go:1632-1667, .id file holder.go:599-619).

The reference persists the set of known node IDs as a protobuf
``.topology`` file so a restarted cluster refuses to serve until every
remembered node has rejoined. This build persists the same facts as JSON.
"""

from __future__ import annotations

import json
import os
import uuid
from dataclasses import dataclass, field


NODE_STATE_READY = "READY"
NODE_STATE_DOWN = "DOWN"


@dataclass(order=True)
class Node:
    """One cluster member (reference cluster.go Node). Ordering is by id —
    the reference keeps nodes sorted by ID so jump-hash placement is
    stable across all members (cluster.go Nodes sort)."""

    id: str
    uri: str = field(compare=False, default="")
    is_coordinator: bool = field(compare=False, default=False)
    state: str = field(compare=False, default=NODE_STATE_READY)

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "uri": self.uri,
            "isCoordinator": self.is_coordinator,
            "state": self.state,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(
            id=d["id"],
            uri=d.get("uri", ""),
            is_coordinator=d.get("isCoordinator", False),
            state=d.get("state", NODE_STATE_READY),
        )


class Topology:
    """Persisted remembered-membership (reference cluster.go:1632-1667)."""

    def __init__(self, node_ids: list[str] | None = None):
        self.node_ids: list[str] = sorted(node_ids or [])

    def contains(self, node_id: str) -> bool:
        return node_id in self.node_ids

    def add(self, node_id: str) -> None:
        if node_id not in self.node_ids:
            self.node_ids.append(node_id)
            self.node_ids.sort()

    def remove(self, node_id: str) -> None:
        if node_id in self.node_ids:
            self.node_ids.remove(node_id)

    # -- persistence --------------------------------------------------------

    @staticmethod
    def path(data_dir: str) -> str:
        return os.path.join(data_dir, ".topology")

    def save(self, data_dir: str) -> None:
        os.makedirs(data_dir, exist_ok=True)
        tmp = self.path(data_dir) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"nodeIDs": self.node_ids}, f)
        os.replace(tmp, self.path(data_dir))

    @classmethod
    def load(cls, data_dir: str) -> "Topology":
        p = cls.path(data_dir)
        if not os.path.exists(p):
            return cls()
        with open(p) as f:
            return cls(json.load(f).get("nodeIDs", []))


def load_or_create_node_id(data_dir: str | None) -> str:
    """Stable node identity across restarts (reference holder.go:599-619
    ``.id`` file). Ephemeral (memory-only) when data_dir is None."""
    if data_dir is None:
        return uuid.uuid4().hex
    os.makedirs(data_dir, exist_ok=True)
    p = os.path.join(data_dir, ".id")
    if os.path.exists(p):
        with open(p) as f:
            return f.read().strip()
    node_id = uuid.uuid4().hex
    with open(p, "w") as f:
        f.write(node_id)
    return node_id
