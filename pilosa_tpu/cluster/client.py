"""Internal node↔node HTTP client (reference: client.go InternalClient
interface :47-76, http/client.go implementation).

All node↔node data-plane traffic goes through this client: query
fan-out, import forwarding, fragment block retrieval for anti-entropy,
whole-fragment streaming for resize, and control messages. JSON replaces
the reference's protobuf codec.
"""

from __future__ import annotations

import gzip
import http.client
import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any

import numpy as np

from pilosa_tpu import deadline
from pilosa_tpu.deadline import DeadlineExceeded
from pilosa_tpu.obs import events as ev
from pilosa_tpu.obs import tracing
from pilosa_tpu.obs.stats import NOP
from pilosa_tpu.testing import faults


class ClientError(Exception):
    def __init__(self, msg: str, code: int = 0):
        super().__init__(msg)
        self.code = code


# -- circuit breaker ---------------------------------------------------------

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half-open"


class CircuitBreaker:
    """Per-peer transport-failure breaker (closed -> open after
    ``threshold`` consecutive transport failures -> half-open probe
    after ``cooldown`` -> closed on success / open on failure).

    Purely ADVISORY: the client never refuses a request because of a
    tripped breaker — routing layers (``dist._group_by_live_owner``)
    consult :meth:`allow` to steer fan-outs around a flapping peer
    BEFORE the membership monitor confirms it down, and recovery flows
    through the half-open probe that routing sends.  HTTP status errors
    do not count (the peer's transport is alive); only connect/send/
    receive failures and timeouts do.

    State transitions are counted on the stats client
    (``circuit_breaker_transitions{peer:..,to:..}``) so breaker churn is
    observable at /metrics and /debug/vars.
    """

    def __init__(
        self,
        peer: str,
        threshold: int = 5,
        cooldown: float = 2.0,
        stats=NOP,
        journal=None,
    ):
        self.peer = peer
        self.threshold = max(1, int(threshold))
        self.cooldown = float(cooldown)
        self.stats = stats
        self.journal = journal  # EventJournal, optional
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, to: str) -> None:
        """Move to ``to`` (lock held) and count the edge."""
        from_state = self._state
        self._state = to
        self.stats.count_with_tags(
            "circuit_breaker_transitions", 1, 1.0,
            (f"peer:{self.peer}", f"to:{to}"),
        )
        if self.journal is not None:
            # EventJournal.record takes its own independent lock and
            # never calls back into the breaker, so recording under this
            # lock cannot deadlock.
            self.journal.record(
                ev.EVENT_CIRCUIT_BREAKER, peer=self.peer,
                from_state=from_state, to=to,
                failures=self._failures,
            )

    def allow(self) -> bool:
        """May a NEW request be routed at this peer right now?  In the
        open state, the first call after the cooldown converts to a
        half-open probe slot (exactly one in flight)."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN:
                if time.monotonic() - self._opened_at >= self.cooldown:
                    self._transition(BREAKER_HALF_OPEN)
                    self._probing = True
                    return True
                return False
            # half-open: one probe at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != BREAKER_CLOSED:
                self._transition(BREAKER_CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == BREAKER_HALF_OPEN or (
                self._state == BREAKER_CLOSED
                and self._failures >= self.threshold
            ):
                self._opened_at = time.monotonic()
                self._transition(BREAKER_OPEN)


class _ConnPool:
    """Keep-alive connection pool per (scheme, host:port).

    urllib opens a fresh TCP connection per request, so every
    node↔node call paid connection setup (plus a TLS handshake on
    https clusters); the serving HTTP stack speaks HTTP/1.1 with
    persistent connections, so pooled ``http.client`` connections cut
    the per-call floor the way the reference's ``http.Transport``
    connection reuse does (reference http/client.go uses Go's pooled
    default transport)."""

    MAX_IDLE_PER_HOST = 8

    def __init__(self, timeout: float, ssl_ctx):
        self._timeout = timeout
        self._ssl_ctx = ssl_ctx
        self._idle: dict[tuple[str, str], list] = {}
        self._lock = threading.Lock()

    def _new_conn(self, scheme: str, netloc: str):
        if scheme == "https":
            import ssl

            ctx = self._ssl_ctx
            if ctx is None:
                ctx = ssl.create_default_context()
            conn = http.client.HTTPSConnection(
                netloc, timeout=self._timeout, context=ctx
            )
        else:
            conn = http.client.HTTPConnection(netloc, timeout=self._timeout)
        # TCP_NODELAY: without it, Nagle + delayed-ACK adds ~40 ms to
        # every small request/response pair on a reused connection
        conn.connect()
        import socket

        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    def _checkout(self, key):
        with self._lock:
            conns = self._idle.get(key)
            if conns:
                return conns.pop()
        return None

    def _checkin(self, key, conn) -> None:
        with self._lock:
            conns = self._idle.setdefault(key, [])
            if len(conns) < self.MAX_IDLE_PER_HOST:
                conns.append(conn)
                return
        conn.close()

    def request(
        self,
        method: str,
        url: str,
        body: bytes | None,
        headers: dict,
        idempotent: bool = True,
        timeout: float | None = None,
    ) -> tuple[int, bytes, str]:
        """(status, body, content-type); raises OSError-family on
        transport failure after one retry on a stale pooled
        connection.  ``idempotent=False`` restricts that retry to
        failures during the SEND phase: once the request has been
        handed to the kernel, the server may have executed it, and
        replaying a non-idempotent request could double-apply it.

        ``timeout`` overrides the pool default for THIS request — the
        deadline-aware client derives it from the remaining budget so a
        request with 0.3s left doesn't block 30s on a stalled peer."""
        parts = urllib.parse.urlsplit(url)
        key = (parts.scheme, parts.netloc)
        path = parts.path + (f"?{parts.query}" if parts.query else "")
        t = self._timeout if timeout is None else timeout
        injected = faults.network_fault(parts.netloc, parts.path, t)
        if injected is not None:
            return injected
        # a pooled connection may have been closed by the server's
        # keep-alive timeout: retry ONCE on a fresh connection, but only
        # when the stale candidate came from the pool
        pooled = self._checkout(key)
        for attempt, conn in enumerate(
            (pooled, None) if pooled is not None else (None,)
        ):
            fresh = conn is None
            if fresh:
                conn = self._new_conn(parts.scheme, parts.netloc)
            conn.timeout = t
            if conn.sock is not None:
                conn.sock.settimeout(t)
            sent = False
            try:
                conn.request(method, path, body=body, headers=headers)
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, OSError):
                conn.close()
                if fresh or (sent and not idempotent):
                    raise
                continue  # stale pooled connection; retry fresh
            if resp.will_close:
                conn.close()
            else:
                self._checkin(key, conn)
            if (resp.headers.get("Content-Encoding") or "").lower() == "gzip":
                # transparent decode: callers asked for gzip on the wire
                # (Accept-Encoding), not in their hands
                data = gzip.decompress(data)
            return (
                resp.status,
                data,
                resp.headers.get("Content-Type") or "",
            )
        raise ClientError("connection retry logic exhausted")  # unreachable


class InternalClient:
    def __init__(
        self,
        timeout: float = 30.0,
        skip_verify: bool = False,
        ca_cert: str | None = None,
        stats=None,
        retry_budget: int = 2,
        backoff_base: float = 0.05,
        backoff_cap: float = 1.0,
        breaker_threshold: int = 5,
        breaker_cooldown: float = 2.0,
        rng_seed: int | None = None,
        journal=None,
    ):
        self.timeout = timeout
        self.stats = NOP if stats is None else stats
        self.journal = journal  # EventJournal; breakers record into it
        # Retry budget: transport failures retry with full-jitter
        # exponential backoff, at most ``retry_budget`` extra attempts
        # per request, never past the remaining deadline, and only for
        # idempotent requests (reference retries imports once,
        # http/client.go; we generalise with a bounded budget).
        self.retry_budget = max(0, int(retry_budget))
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown = breaker_cooldown
        # Seeded so chaos tests replay the same jitter sequence.
        self._rng = random.Random(rng_seed)
        self._rng_lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}
        self._breakers_lock = threading.Lock()
        self._netlocs: dict[str, str] = {}  # uri -> netloc (peers only)
        # TLS: a None context means urlopen verifies with the default
        # verifying context; ``ca_cert`` pins a private CA for
        # intra-cluster certs, and verification is only skipped when the
        # operator explicitly opts in (reference honours tls.skip-verify
        # only when set, server/server.go:230; CA option
        # server/config.go:36-152 tls.ca-certificate).
        self._ssl_ctx = None
        if skip_verify:
            import ssl

            self._ssl_ctx = ssl._create_unverified_context()
        elif ca_cert:
            import ssl

            self._ssl_ctx = ssl.create_default_context(cafile=ca_cert)
        self._pool = _ConnPool(timeout, self._ssl_ctx)

    # -- circuit breakers ---------------------------------------------------

    def _breaker(self, netloc: str) -> CircuitBreaker:
        with self._breakers_lock:
            br = self._breakers.get(netloc)
            if br is None:
                br = CircuitBreaker(
                    netloc,
                    threshold=self.breaker_threshold,
                    cooldown=self.breaker_cooldown,
                    stats=self.stats,
                    journal=self.journal,
                )
                self._breakers[netloc] = br
            return br

    def breaker_states(self) -> dict[str, str]:
        """Current per-peer breaker state by netloc (flight-recorder
        segment field: breaker flaps line up with latency segments)."""
        with self._breakers_lock:
            return {n: br.state for n, br in self._breakers.items()}

    def peer_available(self, uri: str) -> bool:
        """Advisory routing check: False while ``uri``'s breaker is open
        (and not yet due for a half-open probe).  ``dist`` consults this
        to steer fan-outs toward surviving replicas; it never blocks a
        request that routing decides to send anyway."""
        # memoized: this sits on the per-query routing path and peers
        # are a small fixed set — parsing the uri each call shows up in
        # profiles at serving qps
        netloc = self._netlocs.get(uri)
        if netloc is None:
            netloc = urllib.parse.urlsplit(uri).netloc
            self._netlocs[uri] = netloc
        return self._breaker(netloc).allow()

    def _backoff(self, attempt: int) -> float:
        """Full-jitter exponential backoff for retry ``attempt`` (1-based)."""
        ceiling = min(self.backoff_cap, self.backoff_base * (2 ** (attempt - 1)))
        with self._rng_lock:
            return self._rng.random() * ceiling

    # -- plumbing -----------------------------------------------------------

    def _do_full(
        self,
        method: str,
        uri: str,
        path: str,
        body: bytes | None = None,
        content_type: str = "application/json",
        accept: str | None = None,
        idempotent: bool = True,
        retries: int | None = None,
        gzip_ok: bool = False,
    ) -> tuple[bytes, str]:
        """(body, response content-type).

        ``idempotent`` defaults True because every internal endpoint
        today is a merge or find-or-create (imports union bits, schema
        ops are create-if-absent, translate appends are keyed by name,
        resize ops are target-state): replaying any of them is safe.  A
        FUTURE endpoint with execute-once semantics must pass False so
        the pool won't replay it after a stale-connection failure.

        ``retries`` overrides the client retry budget for this call
        (liveness probes pass 0 so a down-check stays prompt)."""
        headers: dict = {}
        if body is not None:
            headers["Content-Type"] = content_type
        if accept is not None:
            headers["Accept"] = accept
        if gzip_ok:
            # large debug snapshots (history/traces/postmortem) compress
            # ~10x; the pool decodes transparently on the way back
            headers["Accept-Encoding"] = "gzip"
        # Propagate the active trace across the node boundary (reference
        # tracing/opentracing.go:58-66 InjectHTTPHeaders).
        span = tracing.active_span()
        if span is not None:
            tracing.get_tracer().inject_headers(span.context, headers)
        netloc = urllib.parse.urlsplit(uri).netloc
        breaker = self._breaker(netloc)
        budget = self.retry_budget if retries is None else max(0, int(retries))
        if not idempotent:
            budget = 0  # backoff retries would replay a received request
        attempt = 0
        while True:
            # Per-hop timeout from the remaining deadline budget: fail
            # fast when it is already spent, and never let the socket
            # outlive what the caller is willing to wait.
            rem = deadline.remaining()
            if rem is not None:
                if rem <= 0:
                    self.stats.count("client_deadline_exceeded", 1, 1.0)
                    raise DeadlineExceeded(
                        f"deadline exceeded before {method} {path} to {netloc}"
                    )
                headers[deadline.HEADER] = format(rem, ".4f")
                hop_timeout = min(self.timeout, rem)
            else:
                hop_timeout = self.timeout
            try:
                status, data, ctype = self._pool.request(
                    method,
                    uri.rstrip("/") + path,
                    body,
                    headers,
                    idempotent=idempotent,
                    timeout=hop_timeout,
                )
            except (http.client.HTTPException, OSError, TimeoutError) as e:
                breaker.record_failure()
                if attempt >= budget:
                    raise ClientError(f"{method} {path}: {e}") from e
                attempt += 1
                delay = self._backoff(attempt)
                rem = deadline.remaining()
                if rem is not None and rem <= delay:
                    # no budget left to wait out the backoff
                    self.stats.count("client_deadline_exceeded", 1, 1.0)
                    raise DeadlineExceeded(
                        f"deadline exceeded retrying {method} {path} to "
                        f"{netloc}: {e}"
                    ) from e
                self.stats.count("client_retries", 1, 1.0)
                time.sleep(delay)
                continue
            breaker.record_success()
            if status >= 400:
                detail = data.decode(errors="replace")[:500]
                raise ClientError(f"{method} {path}: {status} {detail}", status)
            return data, ctype

    def _do(
        self,
        method: str,
        uri: str,
        path: str,
        body: bytes | None = None,
        content_type: str = "application/json",
        gzip_ok: bool = False,
    ) -> bytes:
        return self._do_full(
            method, uri, path, body, content_type, gzip_ok=gzip_ok
        )[0]

    def _json(
        self,
        method: str,
        uri: str,
        path: str,
        obj: Any = None,
        gzip_ok: bool = False,
    ) -> Any:
        body = None if obj is None else json.dumps(obj).encode()
        out = self._do(method, uri, path, body, gzip_ok=gzip_ok)
        return json.loads(out) if out else None

    # -- queries (reference http/client.go QueryNode) -----------------------

    def query_node(
        self, uri: str, index: str, query: str, shards: list[int],
        profile: bool = False,
    ) -> dict:
        """Execute on a remote node against its shard list; returns the
        response dict — ``"wireResults"`` plus, when ``profile`` is set,
        the remote node's ``"profile"`` sub-tree for the coordinator's
        merge (reference executor.go:2416-2434 remoteExec)."""
        req = {"query": query, "shards": shards, "remote": True}
        if profile:
            req["profile"] = True
        return self._json("POST", uri, f"/index/{index}/query", req)

    # -- imports (reference http/client.go Import/ImportRoaring) ------------

    def import_bits(self, uri: str, index: str, field: str, req: dict) -> None:
        """Forward an import slice.  Translated id batches travel as
        packed roaring/array blobs (cluster/wire.py encode_import — the
        reference protobuf-encodes every import, proto.go); key-carrying
        or timestamped requests fall back to JSON."""
        from pilosa_tpu.cluster import wire

        body = wire.encode_import(dict(req, remote=True))
        if body is not None:
            self._do(
                "POST",
                uri,
                f"/index/{index}/field/{field}/import",
                body,
                content_type="application/octet-stream",
            )
            return
        jr = {
            k: (v.tolist() if isinstance(v, np.ndarray) else v)
            for k, v in req.items()
            if not k.startswith("_")
        }
        self._json(
            "POST", uri, f"/index/{index}/field/{field}/import", dict(jr, remote=True)
        )

    def import_roaring(
        self, uri: str, index: str, field: str, shard: int, data: bytes,
        clear: bool = False, view: str = "standard",
    ) -> dict:
        q = f"?remote=true&clear={'true' if clear else 'false'}&view={view}"
        out = self._do(
            "POST",
            uri,
            f"/index/{index}/field/{field}/import-roaring/{shard}{q}",
            data,
            content_type="application/octet-stream",
        )
        return json.loads(out) if out else {}

    # -- fragment data (anti-entropy + resize) ------------------------------

    def fragment_blocks(
        self, uri: str, index: str, field: str, view: str, shard: int
    ) -> list[dict]:
        """Block checksums (reference http/client.go FragmentBlocks)."""
        resp = self._json(
            "GET",
            uri,
            f"/internal/fragment/blocks?index={index}&field={field}"
            f"&view={view}&shard={shard}",
        )
        return resp["blocks"]

    def block_data(
        self, uri: str, index: str, field: str, view: str, shard: int,
        block: int, width: int | None = None,
    ) -> dict:
        """Row/col pairs of one block (reference BlockData). With
        ``width`` (the fragment's shard width) the transfer is a packed
        roaring blob of row*width+col positions; JSON only when the peer
        declines (unencodable row ids or legacy node)."""
        body = json.dumps(
            {"index": index, "field": field, "view": view,
             "shard": shard, "block": block}
        ).encode()
        out, ctype = self._do_full(
            "POST",
            uri,
            "/internal/fragment/block/data",
            body,
            accept="application/octet-stream" if width else None,
        )
        if width and "application/octet-stream" in ctype:
            from pilosa_tpu.storage import roaring

            positions = roaring.deserialize(out)
            w = int(width)
            return {
                "rows": (positions // w).tolist(),
                "cols": (positions % w).tolist(),
            }
        return json.loads(out)

    def attr_blocks(self, uri: str, index: str, field: str | None) -> list[dict]:
        """Attr block checksums (reference http/client.go attr diff calls,
        holder.go:747-839 syncIndex/syncField)."""
        q = f"?index={index}" + (f"&field={field}" if field else "")
        return self._json("GET", uri, f"/internal/attr/blocks{q}")["blocks"]

    def attr_block_data(
        self, uri: str, index: str, field: str | None, block: int
    ) -> dict:
        resp = self._json(
            "POST",
            uri,
            "/internal/attr/block/data",
            {"index": index, "field": field, "block": block},
        )
        return {int(k): v for k, v in resp["attrs"].items()}

    def retrieve_fragment(
        self, uri: str, index: str, field: str, view: str, shard: int
    ) -> bytes:
        """Whole-fragment snapshot stream for resize (reference
        RetrieveShardFromURI http/client.go)."""
        return self._do(
            "GET",
            uri,
            f"/internal/fragment/data?index={index}&field={field}"
            f"&view={view}&shard={shard}",
        )

    def fragment_list(self, uri: str) -> list[dict]:
        """Node's full fragment inventory for resize planning (reference
        fragsByHost cluster.go:687)."""
        return self._json("GET", uri, "/internal/fragments")["fragments"]

    def resize_fetch(self, uri: str, req: dict) -> None:
        """Tell a node to fetch the listed fragments from their sources
        (reference followResizeInstruction cluster.go:1272)."""
        self._json("POST", uri, "/internal/resize/fetch", req)

    # -- online migration (snapshot stream + op-log catch-up) ---------------

    def migrate_begin(
        self, uri: str, index: str, field: str, view: str, shard: int,
        chunk_bytes: int | None = None,
    ) -> dict:
        """Open a migration session on the source: pins a snapshot cut
        and installs the delta tap.  Returns ``{token, size, opN}``."""
        req: dict = {
            "index": index, "field": field, "view": view, "shard": shard,
        }
        if chunk_bytes:
            req["chunkBytes"] = int(chunk_bytes)
        return self._json("POST", uri, "/internal/migrate/begin", req)

    def migrate_chunk(self, uri: str, token: str, offset: int) -> bytes:
        """One snapshot chunk at ``offset``.  GET + offset-addressed =
        idempotent, so a crashed/retried target resumes mid-stream."""
        return self._do(
            "GET", uri,
            f"/internal/migrate/chunk?token={token}&offset={int(offset)}",
        )

    def migrate_delta(self, uri: str, token: str) -> tuple[dict, bytes]:
        """Drain one op-log catch-up round; returns the frame header
        (``ops``, ``pending``) and the raw op-record blob."""
        from pilosa_tpu.cluster import wire

        body = self._do(
            "POST", uri, "/internal/migrate/delta",
            json.dumps({"token": token}).encode(),
        )
        return wire.decode_migrate_frame(body)

    def migrate_end(self, uri: str, token: str) -> None:
        """Close a migration session (uninstalls the tap)."""
        self._json("POST", uri, "/internal/migrate/end", {"token": token})

    def migrate_fetch(self, uri: str, req: dict) -> dict:
        """Tell a target to pull the listed fragments (snapshot stream +
        catch-up) and HOLD the sessions open for the finalize drain."""
        return self._json("POST", uri, "/internal/migrate/fetch", req)

    def migrate_finalize(self, uri: str, req: dict) -> dict:
        """Tell a target to drain final deltas + close its held sessions
        (called after the ownership flip broadcast)."""
        return self._json("POST", uri, "/internal/migrate/finalize", req)

    # -- control plane ------------------------------------------------------

    def send_message(self, uri: str, msg: dict) -> None:
        self._json("POST", uri, "/internal/cluster/message", msg)

    def status(self, uri: str) -> dict:
        return self._json("GET", uri, "/status")

    def version(self, uri: str) -> dict:
        """Liveness double-check (reference confirmNodeDown
        cluster.go:1699-1726 probes /version).  ``retries=0``: a probe
        that backs off just delays the down-confirmation it exists to
        speed up — MembershipMonitor owns the retry cadence."""
        out, _ = self._do_full("GET", uri, "/version", retries=0)
        return json.loads(out) if out else None

    def debug_events(self, uri: str, since: int = 0) -> dict:
        """Pull a peer's local event journal (coordinator timeline merge
        fans out through here)."""
        return self._json("GET", uri, f"/debug/events?since={int(since)}")

    def debug_traces(self, uri: str, limit: int = 100) -> dict:
        """Pull a peer's kept-trace summaries (cluster trace list)."""
        return self._json(
            "GET", uri, f"/debug/traces?limit={int(limit)}", gzip_ok=True
        )

    def debug_trace_spans(self, uri: str, trace_id: str) -> dict:
        """Pull the spans a peer holds for one trace id (cluster trace
        assembly) — kept or merely recent on that node."""
        return self._json(
            "GET", uri, f"/debug/traces?id={trace_id}&spans=true",
            gzip_ok=True,
        )

    def debug_history(
        self,
        uri: str,
        series=None,
        since: int | None = None,
        step: float | None = None,
        limit: int | None = None,
    ) -> dict:
        """Pull a peer's local metrics-history window (the cluster
        timeline merge fans out through here)."""
        params = []
        if series:
            if not isinstance(series, str):
                series = ",".join(series)
            params.append("series=" + urllib.parse.quote(series, safe=""))
        if since is not None:
            params.append(f"since={int(since)}")
        if step is not None:
            params.append(f"step={float(step)}")
        if limit is not None:
            params.append(f"limit={int(limit)}")
        qs = ("?" + "&".join(params)) if params else ""
        return self._json("GET", uri, f"/debug/history{qs}", gzip_ok=True)

    def debug_postmortem(self, uri: str, postmortem_id: str | None = None) -> dict:
        """Pull a peer's sealed crash bundles (the coordinator's
        ``?cluster=true`` merge fans out through here)."""
        qs = f"?id={postmortem_id}" if postmortem_id else ""
        return self._json(
            "GET", uri, f"/debug/postmortem{qs}", gzip_ok=True
        )

    def shards_max(self, uri: str) -> dict:
        """Per-index max shard seen by ``uri`` (reference
        client.go:176 MaxShardByIndex)."""
        return self._json("GET", uri, "/internal/shards/max")

    def nodes(self, uri: str) -> list:
        """Cluster node list as seen by ``uri`` (reference
        client.go:139 Nodes)."""
        return self._json("GET", uri, "/internal/nodes")

    def translate_keys(
        self, uri: str, index: str, field: str | None, keys: list[str]
    ) -> list[int]:
        return self._json(
            "POST",
            uri,
            "/internal/translate/keys",
            {"index": index, "field": field, "keys": keys},
        )["ids"]

    def translate_log(
        self, uri: str, offset: int
    ) -> tuple[list[tuple[str, str, str, int]], int, int]:
        """(entries, new_offset, primary_log_len) since ``offset`` — the
        replica streaming pull (reference translate.go:91-97)."""
        out = self._json(
            "GET", uri, f"/internal/translate/log?offset={int(offset)}", None
        )
        entries = [
            (e[0], e[1], e[2], int(e[3])) for e in out.get("entries", [])
        ]
        return entries, int(out.get("offset", offset)), int(out.get("len", 0))

    def translate_restore(self, uri: str, entries: list) -> dict:
        return self._json(
            "POST", uri, "/internal/translate/restore", {"entries": entries}
        )

    def translate_ids(
        self, uri: str, index: str, field: str | None, ids: list[int]
    ) -> list[str]:
        return self._json(
            "POST",
            uri,
            "/internal/translate/ids",
            {"index": index, "field": field, "ids": ids},
        )["keys"]


class NopInternalClient:
    """reference client.go:103 nopInternalClient."""

    def query_node(self, uri, index, query, shards, profile=False):
        return {"wireResults": []}

    def import_bits(self, uri, index, field, req):
        pass

    def import_roaring(self, uri, index, field, shard, data, clear=False, view="standard"):
        pass

    def fragment_blocks(self, uri, index, field, view, shard):
        return []

    def attr_blocks(self, uri, index, field):
        return []

    def attr_block_data(self, uri, index, field, block):
        return {}

    def block_data(self, uri, index, field, view, shard, block, width=None):
        return {"rows": [], "cols": []}

    def retrieve_fragment(self, uri, index, field, view, shard):
        return b""

    def fragment_list(self, uri):
        return []

    def resize_fetch(self, uri, req):
        pass

    def migrate_begin(self, uri, index, field, view, shard, chunk_bytes=None):
        return {"token": "", "size": 0, "opN": 0}

    def migrate_chunk(self, uri, token, offset):
        return b""

    def migrate_delta(self, uri, token):
        return {"ops": 0, "pending": 0}, b""

    def migrate_end(self, uri, token):
        pass

    def migrate_fetch(self, uri, req):
        return {}

    def migrate_finalize(self, uri, req):
        return {}

    def send_message(self, uri, msg):
        pass

    def status(self, uri):
        return {}

    def version(self, uri):
        return {}

    def debug_events(self, uri, since=0):
        return {"events": [], "nextSeq": since, "truncated": False}

    def debug_history(self, uri, series=None, since=None, step=None,
                      limit=None):
        return {"series": {}, "nextSeq": 0, "truncated": False}

    def debug_traces(self, uri, limit=100):
        return {"traces": []}

    def debug_trace_spans(self, uri, trace_id):
        return {"spans": []}

    def debug_postmortem(self, uri, postmortem_id=None):
        return {"postmortems": [], "latest": None, "postmortem": None}

    def breaker_states(self):
        return {}

    def shards_max(self, uri):
        return {}

    def nodes(self, uri):
        return []

    def translate_keys(self, uri, index, field, keys):
        return []

    def translate_ids(self, uri, index, field, ids):
        return []

    def translate_log(self, uri, offset):
        return [], offset, 0

    def translate_restore(self, uri, entries):
        return {"restored": 0}
