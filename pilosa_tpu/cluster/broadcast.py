"""Typed control-plane messages (reference: broadcast.go, server.go:549-682).

The reference frames 16 protobuf message types with a 1-byte type prefix
(broadcast.go:55-83) and fans them out with parallel HTTP POSTs
(Server.SendSync server.go:646-667). This build frames them as JSON
``{"type": ..., ...payload}`` on ``POST /internal/cluster/message``.
Schema mutations broadcast so every node can serve any query's metadata;
data-plane traffic never rides this path.
"""

from __future__ import annotations

import concurrent.futures
from typing import Protocol

# Message types (reference broadcast.go:55-72)
MSG_CREATE_INDEX = "create-index"
MSG_DELETE_INDEX = "delete-index"
MSG_CREATE_FIELD = "create-field"
MSG_DELETE_FIELD = "delete-field"
MSG_CREATE_VIEW = "create-view"
MSG_DELETE_VIEW = "delete-view"
MSG_CREATE_SHARD = "create-shard"  # reference CreateShardMessage view.go:239-261
MSG_CLUSTER_STATUS = "cluster-status"
MSG_NODE_STATE = "node-state"
MSG_NODE_EVENT = "node-event"
MSG_RESIZE_INSTRUCTION = "resize-instruction"
MSG_RESIZE_COMPLETE = "resize-instruction-complete"
MSG_RESIZE_PREPARE = "resize-prepare"    # pending membership announced
MSG_EPOCH_FLIP = "epoch-flip"            # per-shard ownership flip
MSG_RESIZE_CANCEL = "resize-cancel"      # pending membership dropped
MSG_SET_COORDINATOR = "set-coordinator"
MSG_UPDATE_COORDINATOR = "update-coordinator"
MSG_SCHEMA = "schema"
MSG_RECALCULATE_CACHES = "recalculate-caches"


class Broadcaster(Protocol):
    """reference broadcast.go:30-34 broadcaster."""

    def send_sync(self, msg: dict) -> None: ...

    def send_to(self, node, msg: dict) -> None: ...


class NopBroadcaster:
    """reference broadcast.go:41-52 — lets a Holder/Field run standalone
    with zero network (used pervasively by unit tests)."""

    def send_sync(self, msg: dict) -> None:
        pass

    def send_to(self, node, msg: dict) -> None:
        pass


class HTTPBroadcaster:
    """Parallel fan-out to every peer (reference Server.SendSync
    server.go:646-667)."""

    def __init__(self, cluster, client, local_node_id: str):
        self.cluster = cluster
        self.client = client
        self.local_node_id = local_node_id

    def send_sync(self, msg: dict) -> None:
        peers = [n for n in self.cluster.nodes if n.id != self.local_node_id]
        if not peers:
            return
        with concurrent.futures.ThreadPoolExecutor(max_workers=len(peers)) as ex:
            errs = list(
                ex.map(lambda n: self._send_one(n, msg), peers)
            )
        for e in errs:
            if e is not None:
                raise e

    def _send_one(self, node, msg: dict):
        try:
            self.client.send_message(node.uri, msg)
            return None
        except Exception as e:  # collected, reported by send_sync
            return e

    def send_to(self, node, msg: dict) -> None:
        self.client.send_message(node.uri, msg)
