"""Heartbeat membership + failure detection — the gossip analogue.

The reference detects failures with memberlist UDP/TCP probes
(reference gossip/gossip.go:554-575 probe tuning) and suppresses false
leaves with an HTTP ``/version`` double-check, 10 retries, before the
coordinator accepts a NodeLeave (reference cluster.go:1699-1768
confirmNodeDown / ReceiveEvent).  The reaction is the cluster state
machine: losing fewer than ReplicaN nodes puts the cluster in DEGRADED
(reads keep working via replica failover in the distributed executor);
losing more makes data unavailable (reference determineClusterState
cluster.go:547-558).

A static TPU mesh has no use for full gossip dissemination — membership
only changes through the coordinator-driven resize protocol — so the
monitor keeps the two parts that still matter on a multi-host cluster:

* **liveness probing**: every node round-robins ``GET /version`` over its
  peers (the memberlist probe), marking peers DOWN after confirmation
  retries and READY again the moment a probe succeeds;
* **event delivery**: the coordinator turns confirmed transitions into a
  ``node-state`` broadcast so every member converges on the same view,
  and recomputes the cluster state machine (the follower path simply
  applies the broadcast — reference server.go:633-643 NodeEvent
  handling).
"""

from __future__ import annotations

import logging
import random
import threading
import zlib

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.cluster import Cluster, STATE_RESIZING
from pilosa_tpu.cluster.topology import NODE_STATE_DOWN, NODE_STATE_READY
from pilosa_tpu.obs import events as ev

logger = logging.getLogger(__name__)


class MembershipMonitor:
    """Round-robin peer prober with confirm-down double-checking."""

    def __init__(
        self,
        cluster: Cluster,
        client,
        broadcaster=None,
        probe_interval: float = 1.0,
        confirm_retries: int = 10,  # reference cluster.go:1702
        confirm_interval: float = 0.1,
        on_change=None,
        journal=None,
    ):
        self.cluster = cluster
        self.client = client
        self.broadcaster = broadcaster
        self.journal = journal  # EventJournal, optional
        self.probe_interval = probe_interval
        self.confirm_retries = confirm_retries
        self.confirm_interval = confirm_interval
        self.on_change = on_change  # fn(node_id, new_state)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._rr = 0
        # Per-node seed: every prober jitters its confirm cadence
        # differently, but each node's sequence replays deterministically.
        self._rng = random.Random(zlib.crc32(cluster.node_id.encode()))

    # -- probing ------------------------------------------------------------

    def _peers(self):
        return [n for n in self.cluster.nodes if n.id != self.cluster.node_id]

    def probe_once(self) -> None:
        """Probe the next peer in round-robin order."""
        peers = self._peers()
        if not peers:
            return
        self._rr = (self._rr + 1) % len(peers)
        self.probe_node(peers[self._rr])

    def probe_node(self, node) -> bool:
        """Probe one peer and apply the state transition. Returns liveness."""
        alive = self._ping(node)
        if alive and node.state == NODE_STATE_DOWN:
            self._transition(node, NODE_STATE_READY)
        elif not alive and node.state != NODE_STATE_DOWN:
            if self.confirm_node_down(node):
                # Membership may have changed while we were confirming
                # (e.g. the node was resized out); only mark members.
                if self.cluster.node(node.id) is not None:
                    self._transition(node, NODE_STATE_DOWN)
                return False
        return alive

    def _ping(self, node) -> bool:
        try:
            self.client.version(node.uri)
            return True
        except Exception:
            return False

    def confirm_node_down(self, node) -> bool:
        """Double-check with retries before declaring a peer dead
        (reference confirmNodeDown cluster.go:1699-1726). True = down.

        The inter-probe wait backs off exponentially (capped at 4x the
        base interval) with jitter, so the cluster's probers don't hammer
        a dying peer in lockstep — a peer that is merely restarting gets
        quieter retries spread over the same overall confirmation window
        order of magnitude."""
        for attempt in range(self.confirm_retries):
            if self._stop.is_set():
                return False  # shutting down: never declare peers dead
            if self._ping(node):
                return False
            wait = min(
                self.confirm_interval * (2 ** attempt),
                4 * self.confirm_interval,
            ) * (0.5 + self._rng.random())
            if self._stop.wait(wait):
                return False
        return True

    # -- transitions ---------------------------------------------------------

    def _transition(self, node, state: str) -> None:
        logger.info("node %s -> %s", node.id, state)
        if self.journal is not None:
            self.journal.record(
                ev.EVENT_NODE_STATE, peer=node.id, state=state
            )
        self.cluster.mark_node_state(node.id, state)
        if self.on_change is not None:
            try:
                self.on_change(node.id, state)
            except Exception:
                logger.exception("membership on_change hook failed")
        # The coordinator disseminates so every member converges without
        # full gossip (followers apply MSG_NODE_STATE; reference
        # server.go:633-643). During a resize the resize protocol owns
        # state broadcasts.
        if (
            self.broadcaster is not None
            and self.cluster.is_coordinator
            and self.cluster.state != STATE_RESIZING
        ):
            try:
                self.broadcaster.send_sync(
                    {"type": bc.MSG_NODE_STATE, "node": node.id, "state": state}
                )
            except Exception:
                # Unreachable peers miss the update; their own probes and
                # the next successful broadcast re-converge the view.
                logger.warning(
                    "node-state broadcast failed (view re-converges on "
                    "next probe cycle)",
                    exc_info=True,
                )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.wait(self.probe_interval):
                try:
                    self.probe_once()
                except Exception:
                    logger.exception("membership probe failed")

        self._thread = threading.Thread(
            target=run, name="membership-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
