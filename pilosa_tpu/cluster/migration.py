"""Source-side state for online fragment migration.

The resize protocol (cluster/resize.py) moves a fragment to its new
owner without gating writes: the target streams a snapshot cut in
resumable chunks while writes keep landing on the source, then replays
the op-log delta accrued since the cut in bounded catch-up rounds.
This module is the source half of that protocol:

* ``DeltaTap`` — pinned on the fragment's op-log append point
  (``storage/fragmentfile.py:_append_many``); mirrors every appended
  record in order, so the delta stream replays in exactly file order.
* ``MemoryTapLog`` — a store shim for memory-only fragments (most test
  clusters run storeless: ``fragment.store is None``).  It reuses the
  real ``FragmentFile`` batching machinery but appends to taps only —
  attached for the duration of a migration, detached at end.
* ``MigrationSession`` / ``MigrationRegistry`` — one session per
  in-flight fragment transfer, keyed by an opaque token.  The session
  pins the serialized snapshot (chunk reads are idempotent, so a
  crashed target resumes at its last offset) and the tap.  Sessions
  expire after a TTL so a target that died mid-transfer cannot leak
  taps forever.

Correctness of the cut: the tap is installed *before* the snapshot is
serialized (both under the fragment lock order), so every op is either
in the snapshot, in the tap, or both.  Replaying the tap in order on
top of the snapshot therefore converges to the source state — ops
present in both are harmless because replay applies them in the same
order the source did.
"""

from __future__ import annotations

import itertools
import threading
import time

from pilosa_tpu.storage import roaring
from pilosa_tpu.storage.fragmentfile import FragmentFile

# Default transfer chunk; targets may request smaller (tests exercise
# multi-chunk resume with tiny chunks).
CHUNK_BYTES = 1 << 20

# A session untouched this long is presumed owned by a dead target.
SESSION_TTL = 120.0


class DeltaTap:
    """Ordered accumulator of raw op-log records (bytes)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: list[bytes] = []
        self._count = 0

    def feed(self, records: list[bytes], count: int) -> None:
        # called under the store lock; must be cheap and never raise
        with self._lock:
            self._records.extend(records)
            self._count += count

    def drain(self) -> tuple[bytes, int]:
        """Take everything accumulated so far -> (blob, op_count)."""
        with self._lock:
            records, self._records = self._records, []
            count, self._count = self._count, 0
        return b"".join(records), count

    @property
    def pending(self) -> int:
        with self._lock:
            return self._count


class MemoryTapLog(FragmentFile):
    """Store shim for storeless fragments: the full FragmentFile
    batching/encoding pipeline with the disk append replaced by
    tap-only delivery.  Never touches the filesystem."""

    def __init__(self, fragment):
        # deliberately NOT FragmentFile.__init__: no path, no file
        # handle, and crucially no ``fragment.store = self`` — attach()
        # installs us under the fragment lock.
        self.fragment = fragment
        self.path = "<memory-tap>"
        self.snapshot_queue = None
        self.journal = None
        self.last_snapshot_at = None
        self._lock = threading.Lock()
        self._fh = None
        self._closed = False
        self.op_n = 0
        self.mut_seq = 0
        self._batch_depth = 0
        self._batch_add = []
        self._batch_remove = []
        self._taps = []

    def _append_many(self, records: list[bytes], count: int) -> None:
        if not records:
            return
        with self._lock:
            self.op_n += count
            self.mut_seq += 1
            for tap in self._taps:
                tap.feed(records, count)

    def request_snapshot(self) -> None:
        pass

    def snapshot(self) -> None:
        pass

    def close(self) -> None:
        pass


class MigrationSession:
    """One in-flight fragment transfer, source side."""

    def __init__(self, token: str, fragment, frag_key: tuple):
        self.token = token
        self.fragment = fragment
        self.frag_key = frag_key  # (index, field, view, shard)
        self.tap = DeltaTap()
        self._memlog: MemoryTapLog | None = None
        self._store = None
        self.last_access = time.monotonic()
        self._closed = False
        self.chunk_bytes: int | None = None  # target-requested override
        # Install the tap BEFORE cutting the snapshot: under the
        # fragment lock no op can land between tap install and the cut,
        # so the tap + snapshot together cover every op.
        with fragment._lock:
            store = fragment.store
            if store is None:
                self._memlog = MemoryTapLog(fragment)
                fragment.store = self._memlog
                store = self._memlog
            self._store = store
            store.add_tap(self.tap)
            self.snapshot = roaring.serialize(fragment.all_positions())
        self.size = len(self.snapshot)

    def touch(self) -> None:
        self.last_access = time.monotonic()

    def chunk(self, offset: int, length: int) -> bytes:
        self.touch()
        offset = max(0, int(offset))
        return self.snapshot[offset : offset + max(1, int(length))]

    def delta(self) -> tuple[bytes, int, int]:
        """One catch-up round: (blob, ops_in_blob, ops_still_pending)."""
        self.touch()
        blob, count = self.tap.drain()
        return blob, count, self.tap.pending

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self.fragment._lock:
            if self._store is not None:
                self._store.remove_tap(self.tap)
            if self._memlog is not None and self.fragment.store is self._memlog:
                # detach the shim only if no real store replaced it and
                # no other session still needs it
                if not self._memlog._taps:
                    self.fragment.store = None
        self.snapshot = b""


class MigrationRegistry:
    """Per-node table of live migration sessions (source side)."""

    _ids = itertools.count(1)

    def __init__(self, node_id: str = "", ttl: float = SESSION_TTL):
        self.node_id = node_id
        self.ttl = ttl
        self._lock = threading.Lock()
        self._sessions: dict[str, MigrationSession] = {}

    def begin(self, fragment, frag_key: tuple) -> MigrationSession:
        self._sweep()
        token = f"mig-{self.node_id}-{next(self._ids)}"
        session = MigrationSession(token, fragment, frag_key)
        with self._lock:
            self._sessions[token] = session
        return session

    def get(self, token: str) -> MigrationSession:
        with self._lock:
            session = self._sessions.get(token)
        if session is None:
            raise KeyError(f"unknown migration session: {token}")
        session.touch()
        return session

    def end(self, token: str) -> None:
        with self._lock:
            session = self._sessions.pop(token, None)
        if session is not None:
            session.close()

    def _sweep(self) -> None:
        """Expire sessions whose target stopped pulling (died mid-copy):
        a leaked tap would buffer deltas forever."""
        now = time.monotonic()
        with self._lock:
            dead = [
                t for t, s in self._sessions.items()
                if now - s.last_access > self.ttl
            ]
            expired = [self._sessions.pop(t) for t in dead]
        for s in expired:
            s.close()

    def snapshot_summary(self) -> dict:
        with self._lock:
            return {
                "active": len(self._sessions),
                "sessions": [
                    {
                        "token": s.token,
                        "fragment": list(s.frag_key),
                        "bytes": s.size,
                        "pendingOps": s.tap.pending,
                    }
                    for s in self._sessions.values()
                ],
            }

    def close(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.close()
