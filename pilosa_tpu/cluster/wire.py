"""Wire encoding of query results for node↔node fan-out (reference:
encoding/proto/proto.go QueryResult union, internal/public.proto:72-82).

The reference tags each result with a type id and protobuf-encodes it;
this build tags each result with a type string and JSON-encodes it. Row
bitmaps travel as raw little-endian uint32 words per shard segment
(base64), which keeps the coordinator's reduce step a pure bitwise merge
— ids materialize only at the API edge, like the reference.
"""

from __future__ import annotations

import base64
from typing import Any

import numpy as np

import jax.numpy as jnp

from pilosa_tpu.exec.result import (
    FieldRow,
    GroupCount,
    Pair,
    Row,
    RowIdentifiers,
    ValCount,
)


def encode_result(result: Any) -> Any:
    if isinstance(result, Row):
        return {
            "type": "row",
            "segments": {
                str(shard): base64.b64encode(
                    np.asarray(seg, dtype=np.uint32).tobytes()
                ).decode()
                for shard, seg in result.segments.items()
            },
        }
    if isinstance(result, ValCount):
        return {"type": "valcount", "value": result.value, "count": result.count}
    if isinstance(result, Pair):
        return {"type": "pair", "id": result.id, "key": result.key, "count": result.count}
    if isinstance(result, RowIdentifiers):
        return {"type": "rowids", "rows": result.rows, "keys": result.keys}
    if isinstance(result, GroupCount):
        return {
            "type": "groupcount",
            "group": [
                {"field": g.field, "rowID": g.row_id, "rowKey": g.row_key}
                for g in result.group
            ],
            "count": result.count,
        }
    if isinstance(result, list):
        return {"type": "list", "items": [encode_result(r) for r in result]}
    if isinstance(result, (bool, int, str)) or result is None:
        return {"type": "scalar", "value": result}
    if isinstance(result, np.integer):
        return {"type": "scalar", "value": int(result)}
    raise TypeError(f"unencodable wire result: {type(result)!r}")


def decode_result(obj: Any) -> Any:
    t = obj["type"]
    if t == "row":
        segments = {}
        for shard, b in obj["segments"].items():
            words = np.frombuffer(base64.b64decode(b), dtype=np.uint32)
            segments[int(shard)] = jnp.asarray(words)
        return Row(segments)
    if t == "valcount":
        return ValCount(value=obj["value"], count=obj["count"])
    if t == "pair":
        return Pair(id=obj.get("id") or 0, key=obj.get("key"), count=obj["count"])
    if t == "rowids":
        return RowIdentifiers(rows=obj.get("rows") or [], keys=obj.get("keys"))
    if t == "groupcount":
        return GroupCount(
            group=[
                FieldRow(
                    field=g["field"],
                    row_id=g.get("rowID") or 0,
                    row_key=g.get("rowKey"),
                )
                for g in obj["group"]
            ],
            count=obj["count"],
        )
    if t == "list":
        return [decode_result(r) for r in obj["items"]]
    if t == "scalar":
        return obj["value"]
    raise TypeError(f"unknown wire result type: {t!r}")


def encode_results(results: list[Any]) -> list[Any]:
    return [encode_result(r) for r in results]


def decode_results(results: list[Any]) -> list[Any]:
    return [decode_result(r) for r in results]


# ---------------------------------------------------------------------------
# Binary import payloads (node->node forwarded slices)
# ---------------------------------------------------------------------------
#
# The reference protobuf-encodes every import (encoding/proto/proto.go,
# internal/public.proto:72-82 ImportRequest); JSON int lists are ~15-20
# bytes per value. Here a translated bit-import slice rides as per-shard
# roaring blobs of row*width+offset positions (the fragment's own
# position arithmetic, reference fragment.go:3077-3080) behind a small
# JSON header, and a value-import slice as raw little-endian column and
# value arrays. Key-carrying or timestamped requests stay JSON — they
# are control-plane-sized.

IMPORT_MAGIC = b"PTI1"

# rows whose positions would overflow u64 position arithmetic fall back
# to JSON (the roaring position space is row*width + offset)
_MAX_POS = 2**63


def encode_import(req: dict, width: int | None = None) -> bytes | None:
    """Binary body for a translated import request, or None when the
    request is not binary-eligible (keys, timestamps, missing width)."""
    import json as _json

    from pilosa_tpu.storage import roaring

    if req.get("timestamps") is not None:
        return None
    if "rowKeys" in req or "columnKeys" in req:
        return None
    width = width or req.get("_width")
    cols = req.get("columnIDs")
    if cols is None:
        return None
    cols = np.asarray(cols, dtype=np.uint64)
    clear = bool(req.get("clear"))

    remote = bool(req.get("remote"))
    values = req.get("values")
    if values is not None:
        values = np.asarray(values, dtype=np.int64)
        header = {
            "kind": "values", "clear": clear, "remote": remote,
            "n": int(len(cols)),
        }
        hjson = _json.dumps(header).encode()
        return b"".join(
            [
                IMPORT_MAGIC,
                len(hjson).to_bytes(4, "little"),
                hjson,
                cols.astype("<u8").tobytes(),
                values.astype("<i8").tobytes(),
            ]
        )

    rows = req.get("rowIDs")
    if rows is None or width is None:
        return None
    rows = np.asarray(rows, dtype=np.uint64)
    if len(rows) and int(rows.max()) >= _MAX_POS // width:
        return None  # position arithmetic would overflow; JSON fallback
    offs = cols % np.uint64(width)
    shards = cols // np.uint64(width)
    blobs: list[bytes] = []
    shard_meta: list[dict] = []
    for s in np.unique(shards):
        m = shards == s
        positions = np.unique(rows[m] * np.uint64(width) + offs[m])
        blob = roaring.serialize(positions)
        shard_meta.append({"s": int(s), "len": len(blob)})
        blobs.append(blob)
    header = {
        "kind": "bits",
        "clear": clear,
        "remote": remote,
        "width": int(width),
        "shards": shard_meta,
    }
    hjson = _json.dumps(header).encode()
    return b"".join(
        [IMPORT_MAGIC, len(hjson).to_bytes(4, "little"), hjson] + blobs
    )


# ---------------------------------------------------------------------------
# Migration frames (online resize: snapshot chunks + op-log deltas)
# ---------------------------------------------------------------------------
#
# Same shape as the import payload: magic + 4-byte LE header length +
# JSON header + raw blob.  The blob is either a slice of a serialized
# roaring snapshot (chunk) or concatenated op-log records (delta) —
# both already self-framing, so the header only carries bookkeeping
# (offset / op counts) the receiver needs without parsing the blob.

MIGRATE_MAGIC = b"PTM1"


def encode_migrate_frame(header: dict, blob: bytes = b"") -> bytes:
    import json as _json

    hjson = _json.dumps(header).encode()
    return b"".join(
        [MIGRATE_MAGIC, len(hjson).to_bytes(4, "little"), hjson, blob]
    )


def decode_migrate_frame(body: bytes) -> tuple[dict, bytes]:
    import json as _json

    if body[:4] != MIGRATE_MAGIC:
        raise ValueError("bad migrate frame magic")
    hlen = int.from_bytes(body[4:8], "little")
    header = _json.loads(body[8 : 8 + hlen].decode())
    return header, body[8 + hlen :]


def decode_import(body: bytes) -> dict:
    """Binary import body -> the same request dict shape the JSON path
    produces (numpy arrays instead of lists; always marked remote)."""
    import json as _json

    from pilosa_tpu.storage import roaring

    if body[:4] != IMPORT_MAGIC:
        raise ValueError("bad import payload magic")
    hlen = int.from_bytes(body[4:8], "little")
    header = _json.loads(body[8 : 8 + hlen].decode())
    off = 8 + hlen
    clear = bool(header.get("clear"))
    # the remote marker comes from the SENDER (a forwarding node sets
    # it); a public binary ingest without it still goes through cluster
    # shard routing like the JSON path
    remote = bool(header.get("remote"))
    if header["kind"] == "values":
        n = header["n"]
        cols = np.frombuffer(body, dtype="<u8", count=n, offset=off)
        values = np.frombuffer(
            body, dtype="<i8", count=n, offset=off + 8 * n
        )
        return {
            "columnIDs": cols.astype(np.uint64),
            "values": values.astype(np.int64),
            "clear": clear,
            "remote": remote,
        }
    width = np.uint64(header["width"])
    all_rows: list[np.ndarray] = []
    all_cols: list[np.ndarray] = []
    segments: list[tuple] = []
    for meta in header["shards"]:
        blob = body[off : off + meta["len"]]
        off += meta["len"]
        positions = roaring.deserialize(blob)
        seg_rows = positions // width
        seg_offs = positions % width
        all_rows.append(seg_rows)
        all_cols.append(np.uint64(meta["s"]) * width + seg_offs)
        segments.append((int(meta["s"]), seg_rows, seg_offs))
    rows = np.concatenate(all_rows) if all_rows else np.zeros(0, np.uint64)
    cols = np.concatenate(all_cols) if all_cols else np.zeros(0, np.uint64)
    return {
        "rowIDs": rows,
        "columnIDs": cols,
        "clear": clear,
        "remote": remote,
        # The wire format is already split per shard — hand the split to
        # field.import_bits so the pipeline can skip re-deriving it.
        "_segments": segments,
    }
