"""Wire encoding of query results for node↔node fan-out (reference:
encoding/proto/proto.go QueryResult union, internal/public.proto:72-82).

The reference tags each result with a type id and protobuf-encodes it;
this build tags each result with a type string and JSON-encodes it. Row
bitmaps travel as raw little-endian uint32 words per shard segment
(base64), which keeps the coordinator's reduce step a pure bitwise merge
— ids materialize only at the API edge, like the reference.
"""

from __future__ import annotations

import base64
from typing import Any

import numpy as np

import jax.numpy as jnp

from pilosa_tpu.exec.result import (
    FieldRow,
    GroupCount,
    Pair,
    Row,
    RowIdentifiers,
    ValCount,
)


def encode_result(result: Any) -> Any:
    if isinstance(result, Row):
        return {
            "type": "row",
            "segments": {
                str(shard): base64.b64encode(
                    np.asarray(seg, dtype=np.uint32).tobytes()
                ).decode()
                for shard, seg in result.segments.items()
            },
        }
    if isinstance(result, ValCount):
        return {"type": "valcount", "value": result.value, "count": result.count}
    if isinstance(result, Pair):
        return {"type": "pair", "id": result.id, "key": result.key, "count": result.count}
    if isinstance(result, RowIdentifiers):
        return {"type": "rowids", "rows": result.rows, "keys": result.keys}
    if isinstance(result, GroupCount):
        return {
            "type": "groupcount",
            "group": [
                {"field": g.field, "rowID": g.row_id, "rowKey": g.row_key}
                for g in result.group
            ],
            "count": result.count,
        }
    if isinstance(result, list):
        return {"type": "list", "items": [encode_result(r) for r in result]}
    if isinstance(result, (bool, int, str)) or result is None:
        return {"type": "scalar", "value": result}
    if isinstance(result, np.integer):
        return {"type": "scalar", "value": int(result)}
    raise TypeError(f"unencodable wire result: {type(result)!r}")


def decode_result(obj: Any) -> Any:
    t = obj["type"]
    if t == "row":
        segments = {}
        for shard, b in obj["segments"].items():
            words = np.frombuffer(base64.b64decode(b), dtype=np.uint32)
            segments[int(shard)] = jnp.asarray(words)
        return Row(segments)
    if t == "valcount":
        return ValCount(value=obj["value"], count=obj["count"])
    if t == "pair":
        return Pair(id=obj.get("id") or 0, key=obj.get("key"), count=obj["count"])
    if t == "rowids":
        return RowIdentifiers(rows=obj.get("rows") or [], keys=obj.get("keys"))
    if t == "groupcount":
        return GroupCount(
            group=[
                FieldRow(
                    field=g["field"],
                    row_id=g.get("rowID") or 0,
                    row_key=g.get("rowKey"),
                )
                for g in obj["group"]
            ],
            count=obj["count"],
        )
    if t == "list":
        return [decode_result(r) for r in obj["items"]]
    if t == "scalar":
        return obj["value"]
    raise TypeError(f"unknown wire result type: {t!r}")


def encode_results(results: list[Any]) -> list[Any]:
    return [encode_result(r) for r in results]


def decode_results(results: list[Any]) -> list[Any]:
    return [decode_result(r) for r in results]
