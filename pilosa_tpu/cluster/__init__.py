"""Cluster / distribution layer (reference: cluster.go, broadcast.go,
gossip/, http/client.go).

The reference distributes data by hashing (index, shard) onto one of 256
partitions and jump-hashing partitions onto nodes, with ReplicaN
consecutive ring nodes as replicas (cluster.go:847-934). Queries fan out
shard-wise to owning nodes and reduce at the coordinator of the query
(executor.go:2454-2611). This package keeps that control-plane design —
placement, replication, typed broadcast messages, state machine — while
the TPU build's data plane within one host is a pjit mesh (see
pilosa_tpu.parallel): a "node" here is one host process driving its own
chip slice, and node↔node traffic rides HTTP/JSON instead of the
reference's HTTP/protobuf.
"""

from pilosa_tpu.cluster.hash import jump_hash, partition_hash
from pilosa_tpu.cluster.topology import Node, Topology
from pilosa_tpu.cluster.cluster import (
    Cluster,
    STATE_STARTING,
    STATE_NORMAL,
    STATE_DEGRADED,
    STATE_RESIZING,
)
from pilosa_tpu.cluster.broadcast import Broadcaster, NopBroadcaster
from pilosa_tpu.cluster.client import InternalClient, NopInternalClient

__all__ = [
    "jump_hash",
    "partition_hash",
    "Node",
    "Topology",
    "Cluster",
    "Broadcaster",
    "NopBroadcaster",
    "InternalClient",
    "NopInternalClient",
    "STATE_STARTING",
    "STATE_NORMAL",
    "STATE_DEGRADED",
    "STATE_RESIZING",
]
