"""Distributed query execution: per-shard map-reduce over cluster nodes
(reference: executor.go:2416-2611 mapReduce/mapper/remoteExec).

The coordinator of a query (whichever node received it):

1. translates keys → ids once (reference executor.go:116-209),
2. fans each call out shard-wise — local shards run on this node's
   executor, remote shard groups travel as re-serialized PQL with
   ``remote=true`` + the target's shard list (reference remoteExec),
   EXCEPT when the owner is a slice of the local serving mesh
   (parallel/meshplace.py registry): mesh-local groups are folded with
   the local group into ONE jit-sharded launch over a read-only holder
   facade (cluster/meshexec.py) — collectives instead of sockets,
3. reduces streaming per-call results (union of disjoint-shard bitmap
   segments, count sums, TopN/GroupBy merges),
4. retries a failed node's shards against the remaining replicas
   (reference executor.go:2495-2506), and
5. translates ids → keys in the final results.

Point writes (Set/Clear/attrs) are applied synchronously on EVERY
replica of the target shard (reference executor.go:2140-2207); row/attr
writes with no shard affinity broadcast to all nodes.
"""

from __future__ import annotations

import collections
import concurrent.futures
import contextvars
import logging
import threading
from typing import Any, Callable

from pilosa_tpu import deadline, pql
from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.cluster.cluster import Cluster
from pilosa_tpu.cluster.meshexec import MeshHolderView
from pilosa_tpu.cluster.topology import NODE_STATE_DOWN
from pilosa_tpu.cluster.wire import decode_results
from pilosa_tpu.exec.executor import ExecuteError, Executor, IndexNotFoundError
from pilosa_tpu.exec.result import GroupCount, Pair, Row, RowIdentifiers, ValCount
from pilosa_tpu.obs import devledger, qprofile, tracing
from pilosa_tpu.parallel import meshplace
from pilosa_tpu.pql.ast import Call

# Device cost ledger site for mesh-local collective dispatches.  The
# window wraps the whole facade launch; inner kernel funnels claim their
# own compiles out of it, so this site keeps only mesh-plan-level costs.
_DL_MESH = devledger.site("cluster.mesh_dispatch")

logger = logging.getLogger(__name__)

# Calls whose result is a Row bitmap (reference executeBitmapCallShard
# dispatch, executor.go:653-680).
_BITMAP_CALLS = {
    "Row", "Range", "Difference", "Intersect", "Union", "Xor", "Not", "Shift",
}
# Point writes fanned to all replicas of one shard.
_POINT_WRITES = {"Set", "Clear", "SetColumnAttrs"}
# Writes with no single-shard affinity, broadcast to every node.
_BROADCAST_WRITES = {"SetRowAttrs"}
# Shard-distributed writes that must hit every replica of every shard.
_SHARD_WRITES = {"ClearRow", "Store"}


class NoAvailableReplicaError(ExecuteError):
    pass


class DistributedExecutor:
    """Cluster-aware executor wrapping the single-node Executor."""

    # One fan-out pool per process would serialize independent queries'
    # fans behind each other; per-executor keeps isolation simple and the
    # thread count small (pool threads only block on remote HTTP I/O).
    _FANOUT_WORKERS = 8
    # Distinct shard assignments worth keeping warm facade executors for
    # (assignments only change on membership/breaker events, so steady
    # state uses exactly one entry).
    _MESH_CACHE_ENTRIES = 8
    # Cached mesh plans (one per distinct (index, shard-set)); bigger
    # than the facade cache because plans are tiny and every served
    # index's steady-state shard set deserves a slot.
    _PLAN_CACHE_ENTRIES = 64

    def __init__(
        self, holder, cluster: Cluster, client, translator=None,
        local_executor: Executor | None = None,
    ):
        self.holder = holder
        self.cluster = cluster
        self.client = client
        # share the API's executor when given: serving caches are
        # field-level either way, but the per-executor counters
        # (/debug/vars serving_cache) must reflect the queries actually
        # executed.  translator only applies when WE build the executor —
        # a supplied one keeps its own.
        if local_executor is not None and translator is not None:
            if local_executor.translator is not translator:
                # hard error (not assert: compiled out under -O) — a
                # mismatched translator would silently mistranslate keys
                raise ValueError(
                    "local_executor was built with a different translator"
                )
        self.local = local_executor or Executor(holder, translator=translator)
        # Lazily created: single-node paths never pay for pool threads.
        # Request threads (ThreadingHTTPServer) race on init and against
        # close(), so both go through _pool_lock and a closed flag.
        self._pool: concurrent.futures.ThreadPoolExecutor | None = None
        self._pool_lock = threading.Lock()
        self._closed = False
        # Cluster-on-mesh dispatch: owner groups whose node is registered
        # in the process placement map (parallel/meshplace.py) execute as
        # one jit-sharded launch over a holder facade instead of an HTTP
        # relay.  The per-instance flag lets a single executor opt out
        # (tests that exercise the HTTP plane) without touching the
        # process-wide registry or env kill switch.
        self.mesh_enabled = meshplace.enabled()
        # Facade executors are cached per shard assignment so their
        # field-stack caches stay warm across queries; bounded LRU since
        # assignments churn during resizes.
        self._mesh_cache: collections.OrderedDict = collections.OrderedDict()
        self._mesh_cache_lock = threading.Lock()
        # Mesh PLAN cache: shard->owner grouping is pure python hashing
        # (fnv + jump per shard per query) that dominates the dispatch
        # cost at high qps; plans are reused while the placement token
        # (membership + resize progress) is unchanged, and every hit
        # re-verifies the owners' registry handles so a withdrawn or
        # restarted peer forces a replan.
        self._plan_cache: collections.OrderedDict = collections.OrderedDict()
        self._plan_cache_lock = threading.Lock()
        self._partition_log: collections.deque = collections.deque(maxlen=32)
        self.mesh_dispatches = 0
        self.mesh_fallbacks = 0

    def _fanout_pool(self) -> concurrent.futures.ThreadPoolExecutor:
        with self._pool_lock:
            if self._closed:
                raise ExecuteError("executor is shut down")
            if self._pool is None:
                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=self._FANOUT_WORKERS,
                    thread_name_prefix="pilosa-fanout",
                )
            return self._pool

    def _submit(self, fn, *args):
        """Submit to the fan-out pool under the CALLER's contextvars
        context, so the active trace span crosses the thread hop and
        remote spans still join the coordinator's trace (reference
        tracing/opentracing.go:58-66 header injection)."""
        ctx = contextvars.copy_context()
        return self._fanout_pool().submit(ctx.run, fn, *args)

    def close(self) -> None:
        with self._pool_lock:
            self._closed = True
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    @property
    def _single(self) -> bool:
        return len(self.cluster.nodes) <= 1

    # -- entry points -------------------------------------------------------

    def execute(
        self,
        index_name: str,
        query: str | pql.Query,
        shards: list[int] | None = None,
    ) -> list[Any]:
        if self._single:
            return self.local.execute(index_name, query, shards=shards)
        idx = self.holder.index(index_name)
        if idx is None:
            raise IndexNotFoundError(f"index not found: {index_name}")
        q = pql.parse(query) if isinstance(query, str) else query
        # the write cap guards the COORDINATOR boundary for clustered
        # queries too (reference executor.go:138 runs for every Execute)
        if (
            self.local.max_writes_per_request > 0
            and len(q.write_calls()) > self.local.max_writes_per_request
        ):
            from pilosa_tpu.exec.executor import TooManyWritesError

            raise TooManyWritesError("too many write commands")
        # coordinator-side span (reference executor.go:117); remote fan-out
        # joins it via injected headers in InternalClient._do
        with tracing.start_span("executor.Execute").set_tag("index", index_name):
            results = []
            for call in q.calls:
                tcall = call.clone()
                self.local._translate_call(idx, tcall)
                # per-call span, matching the single-node executor's loop
                # (executor.go:298 executeCall) — profiles and traces of
                # clustered queries then show the same per-call shape
                with tracing.start_span(f"executor.execute{tcall.name}"):
                    results.append(
                        self._execute_call(index_name, idx, tcall, shards)
                    )
            return [
                self.local._translate_result(idx, c, r)
                for c, r in zip(q.calls, results)
            ]

    def rescache_probe(
        self,
        index_name: str,
        q: pql.Query,
        shards: list[int] | None = None,
    ) -> list[Any] | None:
        """Batcher-side semantic cache probe (server/batcher.py).  Only
        the single-node case probes the local full-result cache: on a
        multi-node coordinator a local probe cannot observe remote
        owners' fragment versions, so correctness rides the per-owner
        partial caches underneath (_map_partials / mesh facade) and the
        remote nodes' own executors instead."""
        if self._single:
            return self.local.rescache_probe(index_name, q, shards)
        return None

    def rescache_degraded(
        self,
        index_name: str,
        q: pql.Query,
        shards: list[int] | None = None,
    ) -> list[Any] | None:
        """Degraded-tier probe for the QoS governor (server/qos.py).
        Last-known FULL-result entries only exist on the single-node
        path (same reasoning as :meth:`rescache_probe`): a multi-node
        coordinator falls through and the staged tenant's query runs
        at its reduced weight instead."""
        if self._single:
            return self.local.rescache_degraded(index_name, q, shards)
        return None

    def execute_remote(
        self, index_name: str, query: str | pql.Query, shards: list[int] | None
    ) -> list[Any]:
        """Mapped-node entry (reference Remote:true re-entry,
        executor.go:2520-2555): keys were translated at the coordinator,
        so run raw calls over our shard list and return raw results."""
        idx = self.holder.index(index_name)
        if idx is None:
            raise IndexNotFoundError(f"index not found: {index_name}")
        q = pql.parse(query) if isinstance(query, str) else query
        out = []
        for c in q.calls:
            with tracing.start_span(f"executor.execute{c.name}"):
                out.append(self.local._execute_call(idx, c, shards))
        return out

    # -- per-call routing ---------------------------------------------------

    def _execute_call(
        self, index_name: str, idx, call: Call, shards: list[int] | None
    ) -> Any:
        if call.name in _POINT_WRITES:
            return self._execute_point_write(index_name, idx, call)
        if call.name in _BROADCAST_WRITES:
            return self._execute_broadcast_write(index_name, idx, call)
        all_shards = self.local._shards_for(idx, shards)
        if call.name in _SHARD_WRITES:
            return self._execute_shard_write(index_name, idx, call, all_shards)
        inner = (
            call.children[0]
            if call.name == "Options" and call.children
            else call
        )
        if inner.name == "TopN":
            return self._execute_topn_distributed(
                index_name, idx, call, inner, all_shards
            )
        return self._map_reduce(index_name, idx, call, all_shards)

    def _execute_topn_distributed(
        self, index_name: str, idx, call: Call, inner: Call,
        shards: list[int],
    ) -> list[Pair]:
        """Two-phase distributed TopN (reference executor.go:884-999):
        phase 1 gathers each node's top-n candidates (per-node lists are
        threshold-filtered and truncated to n, so a row ranked n+1 on
        every node but top-k globally would be missed); phase 2
        re-queries ALL nodes for the exact counts of the union of
        candidate ids (``ids=`` disables per-node truncation), so the
        final merge ranks every candidate by its true global count
        before truncating."""
        partials = self._map_partials(index_name, idx, call, shards)
        n, has_n = inner.uint_arg("n")
        _, has_ids = inner.uint_slice_arg("ids")
        if not has_n or not n or has_ids or self._single:
            return _reduce(call, partials)
        cand = sorted({p.id for part in partials for p in (part or [])})
        if not cand:
            return []
        refetch = call.clone()
        target = (
            refetch.children[0]
            if refetch.name == "Options" and refetch.children
            else refetch
        )
        target.args["ids"] = cand
        target.args.pop("n", None)
        partials2 = self._map_partials(index_name, idx, refetch, shards)
        merged = _reduce_topn(refetch, partials2)  # no n -> full merge
        return merged[:n]

    def _shard_of_write(self, call: Call) -> int:
        col, ok = call.uint_arg("_col")
        if not ok:
            raise ExecuteError(f"{call.name}() column argument required")
        return col // (self.holder.n_words * 32)

    def _submit_writes(
        self, index_name: str, call: Call, by_node: dict[str, list[int] | None]
    ) -> dict:
        """Launch a write on several nodes CONCURRENTLY (the reference
        fans replica writes from the coordinating goroutine,
        executor.go:2140-2207); the caller overlaps its local apply and
        then collects with ``_collect_writes``."""
        return {
            self._submit(
                self.client.query_node,
                self._node_by_id(node_id).uri,
                index_name,
                str(call),
                nshards if nshards is not None else [],
            ): node_id
            for node_id, nshards in by_node.items()
        }

    def _node_by_id(self, node_id: str):
        """Resolve a node for fan-out, including JOINING nodes: during an
        online resize a flipped shard routes to a pending-ring member
        that is not in ``cluster.nodes`` until the commit lands."""
        n = self.cluster.node(node_id)
        if n is None and self.cluster.pending_nodes is not None:
            for p in self.cluster.pending_nodes:
                if p.id == node_id:
                    return p
        if n is None:
            raise NoAvailableReplicaError(f"unknown fan-out node {node_id}")
        return n

    @staticmethod
    def _collect_writes(futures: dict) -> list[Any]:
        """Remote raw results; any node failure propagates WITH the
        failing node named — synchronous replica writes must not silently
        drop a replica."""
        out = []
        for f in concurrent.futures.as_completed(futures):
            try:
                out.append(decode_results(f.result()["wireResults"])[0])
            except ClientError as e:
                raise ClientError(
                    f"replica write failed on node {futures[f]}: {e}", e.code
                ) from e
        return out

    def _execute_point_write(self, index_name: str, idx, call: Call) -> Any:
        """Apply on every replica of the shard (reference
        executor.go:2140-2207 executeSetBitField)."""
        shard = self._shard_of_write(call)
        remote: dict[str, list[int] | None] = {}
        local = False
        for node in self.cluster.shard_nodes(index_name, shard):
            if node.id == self.cluster.node_id:
                local = True
            else:
                remote[node.id] = [shard]
        futures = self._submit_writes(index_name, call, remote)
        result = self.local._execute_call(idx, call, [shard]) if local else None
        for r in self._collect_writes(futures):
            result = r if result is None else (result or r)
        return result

    def _execute_broadcast_write(self, index_name: str, idx, call: Call) -> Any:
        remote: dict[str, list[int] | None] = {
            n.id: None for n in self.cluster.nodes if n.id != self.cluster.node_id
        }
        futures = self._submit_writes(index_name, call, remote)
        result = self.local._execute_call(idx, call, None)
        self._collect_writes(futures)
        return result

    def _execute_shard_write(
        self, index_name: str, idx, call: Call, shards: list[int]
    ) -> Any:
        """ClearRow/Store on every replica of every shard so replicas
        never diverge (the reference reaches the same end state via
        mapReduce + anti-entropy repair)."""
        by_replica: dict[str, list[int]] = {}
        for s in shards:
            for node in self.cluster.shard_nodes(index_name, s):
                by_replica.setdefault(node.id, []).append(s)
        local_shards = by_replica.pop(self.cluster.node_id, None)
        futures = self._submit_writes(index_name, call, by_replica)
        changed = False
        if local_shards is not None:
            changed |= bool(self.local._execute_call(idx, call, local_shards))
        changed |= any(bool(r) for r in self._collect_writes(futures))
        return changed

    # -- map-reduce (reference executor.go:2454-2611) -----------------------

    def _map_reduce(
        self, index_name: str, idx, call: Call, shards: list[int]
    ) -> Any:
        return _reduce(call, self._map_partials(index_name, idx, call, shards))

    def _map_partials(
        self, index_name: str, idx, call: Call, shards: list[int]
    ) -> list[Any]:
        pql_text = str(call)
        span = tracing.start_span("executor.mapReduce").set_tag("call", call.name)
        span.set_tag("shards", len(shards))
        with span:
            bad_nodes: set[str] = set()
            partials: list[Any] = []
            pending = list(shards)
            # Partition ladder: mesh collective -> HTTP relay -> replica
            # failover.  A mesh failure mid-query demotes the REST of the
            # query to HTTP (mesh_allowed flips) — it never fails the
            # caller.
            mesh_allowed = self._mesh_on()
            stats = self.holder.stats
            decision = {
                "call": call.name, "index": index_name,
                "shards": len(shards), "meshNodes": 0, "meshShards": 0,
                "httpNodes": 0, "httpShards": 0, "localShards": 0,
                "meshFallback": False,
            }
            while pending:
                # Fail the whole fan-out fast once the request's budget
                # is spent — re-mapping shards onto replicas is pointless
                # work the caller will never see.
                deadline.check(f"mapping {call.name} over {index_name}")
                try:
                    groups = self._group_by_live_owner(
                        index_name, pending, bad_nodes
                    )
                except NoAvailableReplicaError:
                    if not self.cluster.resize_pending:
                        raise
                    # Mid-resize a shard can flip between grouping and
                    # failover: the node that just failed may no longer
                    # be in the (post-flip) owner set at all.  Re-group
                    # once against the current ring with a clean slate.
                    groups = self._group_by_live_owner(
                        index_name, pending, set()
                    )
                pending = []
                # The local shard group ALWAYS runs inline on this
                # request thread — a saturated fan-out pool (slow remote
                # I/O) must never queue purely-local work behind sockets.
                # Mesh-local groups inherit the same invariant: the
                # collective launch below is inline too, only true HTTP
                # legs ride the pool.
                local_shards = groups.pop(self.cluster.node_id, None)
                mesh_groups = (
                    self._mesh_owner_handles(groups) if mesh_allowed else {}
                )
                http_reason = (
                    "disabled" if not self._mesh_on()
                    else "mesh_error" if not mesh_allowed
                    else "off_mesh"
                )
                # Remote nodes are queried CONCURRENTLY (one pool task per
                # node, the reference's goroutine-per-node mapper,
                # executor.go:2520-2555) while the mesh + local groups run
                # on the request thread; results are collected in arrival
                # order and failed nodes' shards re-mapped onto remaining
                # replicas for the next loop pass.
                futures = {
                    self._submit(
                        self._query_remote,
                        self._node_by_id(node_id).uri,
                        node_id,
                        index_name,
                        pql_text,
                        nshards,
                    ): (node_id, nshards)
                    for node_id, nshards in groups.items()
                }
                for nshards in groups.values():
                    stats.count_with_tags(
                        "dist_http_fanout_total", 1, 1.0,
                        (f"reason:{http_reason}",),
                    )
                    decision["httpNodes"] += 1
                    decision["httpShards"] += len(nshards)
                if mesh_groups:
                    try:
                        partials.append(
                            self._mesh_execute(
                                index_name, call, mesh_groups, local_shards
                            )
                        )
                        decision["meshNodes"] += len(mesh_groups) + bool(
                            local_shards
                        )
                        decision["meshShards"] += sum(
                            len(sh) for _, sh in mesh_groups.values()
                        ) + len(local_shards or ())
                        local_shards = None  # folded into the launch
                    except Exception:
                        # Fallback ladder: the collective path must never
                        # fail a query the HTTP relay can still answer —
                        # log the evidence, demote to HTTP, re-map.
                        logger.exception(
                            "mesh dispatch failed for %s on %r; "
                            "falling back to HTTP fan-out",
                            call.name, index_name,
                        )
                        stats.count("dist_mesh_fallback_total", 1)
                        self.mesh_fallbacks += 1
                        decision["meshFallback"] = True
                        mesh_allowed = False
                        for _, nshards in mesh_groups.values():
                            pending.extend(nshards)
                if local_shards is not None:
                    decision["localShards"] += len(local_shards)
                    # local partial through the semantic cache: repeat
                    # fan-outs reuse this node's partial under its own
                    # fragment version subvector (exec/rescache.py)
                    partials.append(
                        self.local.cached_execute_call(idx, call, local_shards)
                    )
                if futures:
                    fanout = tracing.start_span("dist.httpFanout")
                    fanout.set_tag("peers", len(futures))
                    fanout.set_tag("reason", http_reason)
                    with fanout:
                        for fut in concurrent.futures.as_completed(futures):
                            node_id, nshards = futures[fut]
                            try:
                                partials.append(fut.result())
                            except ClientError:
                                # Failover: re-map this node's shards onto
                                # remaining replicas (reference
                                # executor.go:2495-2506).
                                bad_nodes.add(node_id)
                                pending.extend(nshards)
            self._partition_log.append(decision)
            if not partials:
                partials = [self.local._execute_call(idx, call, [])]
            return partials

    # -- cluster-on-mesh collective dispatch --------------------------------

    def _mesh_on(self) -> bool:
        return self.mesh_enabled and meshplace.enabled()

    def _mesh_owner_handles(self, groups: dict) -> dict:
        """Pop every owner group whose node is registered as mesh-local;
        returns node id -> (placement handle, shards).  What remains in
        ``groups`` is the off-mesh HTTP remainder."""
        placement = meshplace.default_placement()
        out = {}
        for node_id in list(groups):
            h = placement.handle(node_id)
            if h is not None:
                out[node_id] = (h, groups.pop(node_id))
        return out

    def _mesh_executor_for(self, owners: dict) -> Executor:
        """Facade executor for one shard assignment (node id ->
        (holder, generation, shards)); cached so repeated queries over a
        stable assignment keep their device stacks warm."""
        key = tuple(sorted(
            (nid, gen, tuple(sorted(sh)))
            for nid, (holder, gen, sh) in owners.items()
        ))
        with self._mesh_cache_lock:
            hit = self._mesh_cache.get(key)
            if hit is not None:
                self._mesh_cache.move_to_end(key)
                return hit
            view = MeshHolderView(
                self.holder,
                {
                    nid: (holder, tuple(sorted(sh)))
                    for nid, (holder, gen, sh) in owners.items()
                },
            )
            ex = Executor(
                view,
                translator=self.local.translator,
                rescache_entries=self.local.rescache.max_entries,
                rescache_promote_hits=self.local.rescache.promote_hits,
                rescache_demote_deltas=self.local.rescache.demote_deltas,
            )
            self._mesh_cache[key] = ex
            while len(self._mesh_cache) > self._MESH_CACHE_ENTRIES:
                self._mesh_cache.popitem(last=False)
            return ex

    def _mesh_execute(
        self, index_name: str, call: Call, mesh_groups: dict,
        local_shards: list[int] | None,
    ) -> Any:
        """Answer every mesh-local owner group (plus the coordinator's
        own shards, folded in) as ONE jit-sharded launch over the holder
        facade — inline on the request thread, same invariant as the
        plain local group."""
        owners = {
            nid: (h.holder, h.generation, nshards)
            for nid, (h, nshards) in mesh_groups.items()
        }
        if local_shards:
            # generation 0: the coordinator's holder identity is tied to
            # this executor's lifetime, not a registry entry
            owners[self.cluster.node_id] = (self.holder, 0, local_shards)
        shards = sorted(s for _, _, sh in owners.values() for s in sh)
        ex = self._mesh_executor_for(owners)
        fidx = ex.holder.index(index_name)
        if fidx is None:
            raise IndexNotFoundError(f"index not found: {index_name}")
        span = tracing.start_span("dist.meshDispatch")
        span.set_tag("call", call.name).set_tag("nodes", len(owners))
        span.set_tag("shards", len(shards))
        with span, qprofile.span(
            "meshDispatch", nodes=len(owners), shards=len(shards)
        ), _DL_MESH.launch(
            sig=f"{call.name} nodes{len(owners)} shards{len(shards)}"
        ):
            # through the facade executor's own semantic cache: the
            # partial is keyed by the owners' REAL fragment versions
            # (MeshView resolves to live fragments), and the facade
            # executor itself is cached per shard assignment, so a
            # resize epoch / shard flip rotates to a fresh cache while
            # fragment epochs fence any survivor entries
            out = ex.cached_execute_call(fidx, call, shards)
        self.mesh_dispatches += 1
        self.holder.stats.count("dist_mesh_local_total", 1)
        return out

    def mesh_complete(
        self,
        index_name: str,
        query: pql.Query,
        shards: list[int] | None = None,
    ) -> bool:
        """True when every owner of the query's shards is a slice of the
        local mesh — such a read can ride the continuous-batching plane
        (server/batcher.py) because it dispatches as one sharded launch
        with no HTTP subrequests to wait on."""
        if self._single or not self._mesh_on():
            return False
        if query.write_calls():
            return False
        idx = self.holder.index(index_name)
        if idx is None:
            return False
        return self._plan_mesh_batch(index_name, idx, shards) is not None

    def _placement_token(self) -> tuple:
        """Validity fence for cached mesh plans: changes whenever the
        shard->owner mapping can change — membership (node ids), resize
        epoch, or per-shard flip progress mid-resize."""
        cl = self.cluster
        flips = len(cl.flipped) if cl.pending_nodes is not None else -1
        return (cl.epoch, flips, tuple(n.id for n in cl.nodes))

    def _plan_mesh_batch(self, index_name: str, idx, shards: list[int] | None):
        """Partition one batched query; returns (assignment key, owners,
        shard list) when the whole query is mesh-resolvable, else None.

        The owner grouping is cached per (index, shard set) under a
        placement token — grouping hashes every shard through the ring
        per call, which would otherwise dominate the mesh hot path.  A
        cache hit still re-resolves every peer's registry handle, so a
        withdrawn/restarted node invalidates the plan immediately."""
        try:
            shard_list = self.local._shards_for(idx, shards)
        except ExecuteError:
            return None
        token = self._placement_token()
        ckey = (index_name, tuple(shard_list))
        placement = meshplace.default_placement()
        with self._plan_cache_lock:
            hit = self._plan_cache.get(ckey)
            if hit is not None:
                self._plan_cache.move_to_end(ckey)
        if hit is not None and hit[0] == token:
            owners = {}
            for nid, nshards in hit[1].items():
                if nid == self.cluster.node_id:
                    owners[nid] = (self.holder, 0, nshards)
                    continue
                h = placement.handle(nid)
                if h is None:
                    owners = None  # peer left the mesh; replan below
                    break
                owners[nid] = (h.holder, h.generation, nshards)
            if owners:
                key = tuple(sorted(
                    (nid, gen, tuple(sorted(sh)))
                    for nid, (holder, gen, sh) in owners.items()
                ))
                return key, owners, shard_list
        try:
            groups = self._group_by_live_owner(index_name, shard_list, set())
        except ExecuteError:
            return None
        local = groups.pop(self.cluster.node_id, None)
        owners = {}
        for nid, nshards in groups.items():
            h = placement.handle(nid)
            if h is None:
                return None
            owners[nid] = (h.holder, h.generation, nshards)
        if local:
            owners[self.cluster.node_id] = (self.holder, 0, local)
        if not owners:
            return None
        with self._plan_cache_lock:
            self._plan_cache[ckey] = (
                token,
                {
                    nid: tuple(sorted(sh))
                    for nid, (holder, gen, sh) in owners.items()
                },
            )
            while len(self._plan_cache) > self._PLAN_CACHE_ENTRIES:
                self._plan_cache.popitem(last=False)
        key = tuple(sorted(
            (nid, gen, tuple(sorted(sh)))
            for nid, (holder, gen, sh) in owners.items()
        ))
        return key, owners, shard_list

    def execute_batch(
        self, index_name: str, queries: list[tuple]
    ) -> list[Any]:
        """Cross-request micro-batch entry (server/batcher.py): queries
        whose shard owners all resolve to the local mesh dispatch as one
        sharded ``Executor.execute_batch`` launch per assignment, demuxed
        per query; everything else (off-mesh owners, writes, planning
        failures) falls back to the per-query distributed path.  Result
        slots mirror ``Executor.execute_batch``: a list of per-call
        results, or an Exception instance for that query alone."""
        if self._single:
            return self.local.execute_batch(index_name, queries)
        idx = self.holder.index(index_name)
        if idx is None:
            err = IndexNotFoundError(f"index not found: {index_name}")
            return [err for _ in queries]
        out: list[Any] = [None] * len(queries)
        fallback: list[tuple] = []  # (slot, query, shards)
        flights: dict[tuple, list] = {}  # assignment key -> [(slot, q, shard_list)]
        plans: dict[tuple, dict] = {}  # assignment key -> owners
        span = tracing.start_span("executor.ExecuteBatch")
        span.set_tag("index", index_name).set_tag("queries", len(queries))
        with span:
            for slot, (query, qshards) in enumerate(queries):
                try:
                    q = pql.parse(query) if isinstance(query, str) else query
                except Exception as e:  # parse errors belong to their slot
                    out[slot] = e
                    continue
                plan = None
                if self._mesh_on() and not q.write_calls():
                    plan = self._plan_mesh_batch(index_name, idx, qshards)
                if plan is None:
                    fallback.append((slot, q, qshards))
                    continue
                key, owners, shard_list = plan
                plans[key] = owners
                flights.setdefault(key, []).append((slot, q, shard_list))
            for key, items in flights.items():
                try:
                    ex = self._mesh_executor_for(plans[key])
                    mspan = tracing.start_span("dist.meshDispatch")
                    mspan.set_tag("queries", len(items))
                    with mspan, qprofile.span(
                        "meshDispatch", queries=len(items)
                    ), _DL_MESH.launch(sig=f"batch q{len(items)}"):
                        got = ex.execute_batch(
                            index_name,
                            [(q, list(sh)) for _, q, sh in items],
                        )
                    for (slot, _, _), res in zip(items, got):
                        out[slot] = res
                    self.mesh_dispatches += 1
                    self.holder.stats.count(
                        "dist_mesh_local_total", len(items)
                    )
                    self._partition_log.append({
                        "call": "<batch>", "index": index_name,
                        "queries": len(items),
                        "meshNodes": len(plans[key]),
                        "meshShards": sum(
                            len(sh) for _, _, sh in plans[key].values()
                        ),
                        "httpNodes": 0, "httpShards": 0, "localShards": 0,
                        "meshFallback": False,
                    })
                except Exception:
                    # Same fallback ladder as _map_partials: a mesh
                    # failure demotes this flight to per-query HTTP.
                    logger.exception(
                        "mesh batch dispatch failed on %r; "
                        "re-running %d queries individually",
                        index_name, len(items),
                    )
                    self.holder.stats.count("dist_mesh_fallback_total", 1)
                    self.mesh_fallbacks += 1
                    fallback.extend(
                        (slot, q, list(sh)) for slot, q, sh in items
                    )
            for slot, q, qshards in fallback:
                try:
                    out[slot] = self.execute(index_name, q, shards=qshards)
                except Exception as e:  # isolate per query, like Executor
                    out[slot] = e
        return out

    def snapshot(self) -> dict:
        """/debug/vars ``dist`` block: placement map plus recent per-call
        partition decisions (docs/serving.md "Cluster on the mesh")."""
        return {
            "meshEnabled": self._mesh_on(),
            "singleNode": self._single,
            "placement": meshplace.default_placement().snapshot(),
            "meshDispatches": self.mesh_dispatches,
            "meshFallbacks": self.mesh_fallbacks,
            "recentPartitions": list(self._partition_log),
            # facade executors' partial caches, aggregated: mesh-leg
            # repeats served without re-launching the collective
            "meshRescache": self._mesh_rescache_totals(),
        }

    def _mesh_rescache_totals(self) -> dict:
        totals = {"hits": 0, "misses": 0, "invalidations": 0, "entries": 0}
        with self._mesh_cache_lock:
            executors = list(self._mesh_cache.values())
        for ex in executors:
            snap = ex.rescache.snapshot()
            totals["hits"] += snap["hits"]
            totals["misses"] += snap["misses"]
            totals["invalidations"] += snap["invalidations"]
            totals["entries"] += snap["entries"]
        return totals

    def _query_remote(
        self,
        uri: str,
        node_id: str,
        index_name: str,
        pql_text: str,
        shards: list[int],
    ) -> Any:
        """One fan-out leg: remote query plus sub-profile graft.  When the
        coordinator's query is being profiled the remote node returns its
        own profile dict in the response envelope, and we hang it off the
        current span so ``?profile=true`` shows the whole cluster tree."""
        want = qprofile.profiling()
        # real tracing span (not just a profile node): the remote node's
        # http.query span parents to THIS span, so a cluster-assembled
        # trace shows coordinator -> fanout -> peer as one tree
        fanout = tracing.start_span("dist.fanout")
        fanout.set_tag("peer", node_id).set_tag("shards", len(shards))
        with fanout, qprofile.span("fanout", node=node_id, shards=len(shards)):
            resp = self.client.query_node(
                uri, index_name, pql_text, shards, profile=want
            )
            if want:
                qprofile.add_subprofile(node_id, resp.get("profile"))
            return decode_results(resp["wireResults"])[0]

    def _peer_available(self, node) -> bool:
        """Circuit-breaker routing check — local node is always
        available (no transport involved), and a client without breakers
        (NopInternalClient, test doubles) never vetoes a peer."""
        if node.id == self.cluster.node_id:
            return True
        check = getattr(self.client, "peer_available", None)
        if check is None:
            return True
        return check(node.uri)

    def _group_by_live_owner(
        self, index_name: str, shards: list[int], bad_nodes: set[str]
    ) -> dict[str, list[int]]:
        groups: dict[str, list[int]] = {}
        for s in shards:
            owner = None
            fallback = None
            for node in self.cluster.shard_nodes(index_name, s):
                if node.id in bad_nodes or node.state == NODE_STATE_DOWN:
                    continue
                # Two-pass selection: prefer a replica whose circuit
                # breaker admits traffic, so fan-outs route around a
                # flapping peer BEFORE membership confirms it down; if
                # every live replica is tripped, degrade gracefully and
                # use the first anyway (it may have just recovered, and
                # failover still covers us if it hasn't).
                if fallback is None:
                    fallback = node
                if self._peer_available(node):
                    owner = node
                    break
            if owner is None:
                owner = fallback
            if owner is None:
                raise NoAvailableReplicaError(
                    f"no available replica for shard {s} of {index_name!r}"
                )
            groups.setdefault(owner.id, []).append(s)
        return groups


# -- reduce functions (reference executor.go per-call reduceFns) ------------


def _reduce(call: Call, partials: list[Any]) -> Any:
    name = call.name
    if name == "Options" and call.children:
        name = call.children[0].name
    fn = _REDUCERS.get(name)
    if fn is None:
        if name in _BITMAP_CALLS:
            fn = _reduce_rows_union
        else:
            raise ExecuteError(f"no reducer for call {call.name!r}")
    return fn(call, partials)


def _reduce_rows_union(call: Call, partials: list[Any]) -> Row:
    out = Row({})
    for p in partials:
        if p is not None:
            out = out.union(p)
    return out


def _reduce_count(call: Call, partials: list[Any]) -> int:
    return sum(int(p) for p in partials if p is not None)


def _reduce_sum(call: Call, partials: list[Any]) -> ValCount:
    out = ValCount()
    for p in partials:
        if p is not None:
            out = ValCount(out.value + p.value, out.count + p.count)
    return out


def _reduce_min_max(maximal: bool) -> Callable:
    def fn(call: Call, partials: list[Any]) -> ValCount:
        out = None
        for p in partials:
            if p is None or p.count == 0:
                continue
            if out is None or (p.value > out.value) == maximal:
                out = ValCount(p.value, p.count)
            elif p.value == out.value:
                out = ValCount(out.value, out.count + p.count)
        return out or ValCount()

    return fn


def _reduce_min_max_row(maximal: bool) -> Callable:
    def fn(call: Call, partials: list[Any]) -> Pair:
        out = None
        for p in partials:
            if p is None or p.count == 0:
                continue
            if out is None or (p.id > out.id) == maximal:
                out = Pair(id=p.id, key=p.key, count=p.count)
            elif p.id == out.id:
                out = Pair(id=out.id, key=out.key, count=out.count + p.count)
        return out or Pair()

    return fn


def _reduce_topn(call: Call, partials: list[Any]) -> list[Pair]:
    counts: dict[int, int] = {}
    for p in partials:
        for pair in p or []:
            counts[pair.id] = counts.get(pair.id, 0) + pair.count
    n, _ = call.uint_arg("n")
    pairs = sorted(
        (Pair(id=i, count=c) for i, c in counts.items()),
        key=lambda pr: (-pr.count, pr.id),
    )
    if n:
        pairs = pairs[:n]
    return pairs


def _reduce_rows_call(call: Call, partials: list[Any]) -> RowIdentifiers:
    ids: set[int] = set()
    for p in partials:
        if p is not None:
            ids.update(p.rows)
    rows = sorted(ids)
    limit, ok = call.uint_arg("limit")
    if ok and limit is not None:
        rows = rows[:limit]
    return RowIdentifiers(rows=rows)


def _reduce_groupby(call: Call, partials: list[Any]) -> list[GroupCount]:
    merged: dict[tuple, GroupCount] = {}
    for p in partials:
        for gc in p or []:
            key = tuple((g.field, g.row_id, g.row_key) for g in gc.group)
            if key in merged:
                merged[key] = GroupCount(gc.group, merged[key].count + gc.count)
            else:
                merged[key] = GroupCount(gc.group, gc.count)
    out = sorted(
        merged.values(), key=lambda gc: [g.row_id for g in gc.group]
    )
    limit, ok = call.uint_arg("limit")
    if ok and limit is not None:
        out = out[:limit]
    return [gc for gc in out if gc.count > 0]


def _reduce_bool_or(call: Call, partials: list[Any]) -> bool:
    return any(bool(p) for p in partials if p is not None)


def _reduce_first(call: Call, partials: list[Any]) -> Any:
    return partials[0] if partials else None


_REDUCERS: dict[str, Callable] = {
    "Count": _reduce_count,
    "Sum": _reduce_sum,
    "Min": _reduce_min_max(False),
    "Max": _reduce_min_max(True),
    "MinRow": _reduce_min_max_row(False),
    "MaxRow": _reduce_min_max_row(True),
    "TopN": _reduce_topn,
    "Rows": _reduce_rows_call,
    "GroupBy": _reduce_groupby,
    "ClearRow": _reduce_bool_or,
    "Store": _reduce_bool_or,
    "Set": _reduce_bool_or,
    "Clear": _reduce_bool_or,
    "SetRowAttrs": _reduce_first,
    "SetColumnAttrs": _reduce_first,
}
