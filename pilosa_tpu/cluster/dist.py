"""Distributed query execution: per-shard map-reduce over cluster nodes
(reference: executor.go:2416-2611 mapReduce/mapper/remoteExec).

The coordinator of a query (whichever node received it):

1. translates keys → ids once (reference executor.go:116-209),
2. fans each call out shard-wise — local shards run on this node's
   executor, remote shard groups travel as re-serialized PQL with
   ``remote=true`` + the target's shard list (reference remoteExec),
3. reduces streaming per-call results (union of disjoint-shard bitmap
   segments, count sums, TopN/GroupBy merges),
4. retries a failed node's shards against the remaining replicas
   (reference executor.go:2495-2506), and
5. translates ids → keys in the final results.

Point writes (Set/Clear/attrs) are applied synchronously on EVERY
replica of the target shard (reference executor.go:2140-2207); row/attr
writes with no shard affinity broadcast to all nodes.
"""

from __future__ import annotations

import concurrent.futures
import contextvars
import threading
from typing import Any, Callable

from pilosa_tpu import deadline, pql
from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.cluster.cluster import Cluster
from pilosa_tpu.cluster.topology import NODE_STATE_DOWN
from pilosa_tpu.cluster.wire import decode_results
from pilosa_tpu.exec.executor import ExecuteError, Executor, IndexNotFoundError
from pilosa_tpu.exec.result import GroupCount, Pair, Row, RowIdentifiers, ValCount
from pilosa_tpu.obs import qprofile, tracing
from pilosa_tpu.pql.ast import Call

# Calls whose result is a Row bitmap (reference executeBitmapCallShard
# dispatch, executor.go:653-680).
_BITMAP_CALLS = {
    "Row", "Range", "Difference", "Intersect", "Union", "Xor", "Not", "Shift",
}
# Point writes fanned to all replicas of one shard.
_POINT_WRITES = {"Set", "Clear", "SetColumnAttrs"}
# Writes with no single-shard affinity, broadcast to every node.
_BROADCAST_WRITES = {"SetRowAttrs"}
# Shard-distributed writes that must hit every replica of every shard.
_SHARD_WRITES = {"ClearRow", "Store"}


class NoAvailableReplicaError(ExecuteError):
    pass


class DistributedExecutor:
    """Cluster-aware executor wrapping the single-node Executor."""

    # One fan-out pool per process would serialize independent queries'
    # fans behind each other; per-executor keeps isolation simple and the
    # thread count small (pool threads only block on remote HTTP I/O).
    _FANOUT_WORKERS = 8

    def __init__(
        self, holder, cluster: Cluster, client, translator=None,
        local_executor: Executor | None = None,
    ):
        self.holder = holder
        self.cluster = cluster
        self.client = client
        # share the API's executor when given: serving caches are
        # field-level either way, but the per-executor counters
        # (/debug/vars serving_cache) must reflect the queries actually
        # executed.  translator only applies when WE build the executor —
        # a supplied one keeps its own.
        if local_executor is not None and translator is not None:
            if local_executor.translator is not translator:
                # hard error (not assert: compiled out under -O) — a
                # mismatched translator would silently mistranslate keys
                raise ValueError(
                    "local_executor was built with a different translator"
                )
        self.local = local_executor or Executor(holder, translator=translator)
        # Lazily created: single-node paths never pay for pool threads.
        # Request threads (ThreadingHTTPServer) race on init and against
        # close(), so both go through _pool_lock and a closed flag.
        self._pool: concurrent.futures.ThreadPoolExecutor | None = None
        self._pool_lock = threading.Lock()
        self._closed = False

    def _fanout_pool(self) -> concurrent.futures.ThreadPoolExecutor:
        with self._pool_lock:
            if self._closed:
                raise ExecuteError("executor is shut down")
            if self._pool is None:
                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=self._FANOUT_WORKERS,
                    thread_name_prefix="pilosa-fanout",
                )
            return self._pool

    def _submit(self, fn, *args):
        """Submit to the fan-out pool under the CALLER's contextvars
        context, so the active trace span crosses the thread hop and
        remote spans still join the coordinator's trace (reference
        tracing/opentracing.go:58-66 header injection)."""
        ctx = contextvars.copy_context()
        return self._fanout_pool().submit(ctx.run, fn, *args)

    def close(self) -> None:
        with self._pool_lock:
            self._closed = True
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    @property
    def _single(self) -> bool:
        return len(self.cluster.nodes) <= 1

    # -- entry points -------------------------------------------------------

    def execute(
        self,
        index_name: str,
        query: str | pql.Query,
        shards: list[int] | None = None,
    ) -> list[Any]:
        if self._single:
            return self.local.execute(index_name, query, shards=shards)
        idx = self.holder.index(index_name)
        if idx is None:
            raise IndexNotFoundError(f"index not found: {index_name}")
        q = pql.parse(query) if isinstance(query, str) else query
        # the write cap guards the COORDINATOR boundary for clustered
        # queries too (reference executor.go:138 runs for every Execute)
        if (
            self.local.max_writes_per_request > 0
            and len(q.write_calls()) > self.local.max_writes_per_request
        ):
            from pilosa_tpu.exec.executor import TooManyWritesError

            raise TooManyWritesError("too many write commands")
        # coordinator-side span (reference executor.go:117); remote fan-out
        # joins it via injected headers in InternalClient._do
        with tracing.start_span("executor.Execute").set_tag("index", index_name):
            results = []
            for call in q.calls:
                tcall = call.clone()
                self.local._translate_call(idx, tcall)
                # per-call span, matching the single-node executor's loop
                # (executor.go:298 executeCall) — profiles and traces of
                # clustered queries then show the same per-call shape
                with tracing.start_span(f"executor.execute{tcall.name}"):
                    results.append(
                        self._execute_call(index_name, idx, tcall, shards)
                    )
            return [
                self.local._translate_result(idx, c, r)
                for c, r in zip(q.calls, results)
            ]

    def execute_remote(
        self, index_name: str, query: str | pql.Query, shards: list[int] | None
    ) -> list[Any]:
        """Mapped-node entry (reference Remote:true re-entry,
        executor.go:2520-2555): keys were translated at the coordinator,
        so run raw calls over our shard list and return raw results."""
        idx = self.holder.index(index_name)
        if idx is None:
            raise IndexNotFoundError(f"index not found: {index_name}")
        q = pql.parse(query) if isinstance(query, str) else query
        out = []
        for c in q.calls:
            with tracing.start_span(f"executor.execute{c.name}"):
                out.append(self.local._execute_call(idx, c, shards))
        return out

    # -- per-call routing ---------------------------------------------------

    def _execute_call(
        self, index_name: str, idx, call: Call, shards: list[int] | None
    ) -> Any:
        if call.name in _POINT_WRITES:
            return self._execute_point_write(index_name, idx, call)
        if call.name in _BROADCAST_WRITES:
            return self._execute_broadcast_write(index_name, idx, call)
        all_shards = self.local._shards_for(idx, shards)
        if call.name in _SHARD_WRITES:
            return self._execute_shard_write(index_name, idx, call, all_shards)
        inner = (
            call.children[0]
            if call.name == "Options" and call.children
            else call
        )
        if inner.name == "TopN":
            return self._execute_topn_distributed(
                index_name, idx, call, inner, all_shards
            )
        return self._map_reduce(index_name, idx, call, all_shards)

    def _execute_topn_distributed(
        self, index_name: str, idx, call: Call, inner: Call,
        shards: list[int],
    ) -> list[Pair]:
        """Two-phase distributed TopN (reference executor.go:884-999):
        phase 1 gathers each node's top-n candidates (per-node lists are
        threshold-filtered and truncated to n, so a row ranked n+1 on
        every node but top-k globally would be missed); phase 2
        re-queries ALL nodes for the exact counts of the union of
        candidate ids (``ids=`` disables per-node truncation), so the
        final merge ranks every candidate by its true global count
        before truncating."""
        partials = self._map_partials(index_name, idx, call, shards)
        n, has_n = inner.uint_arg("n")
        _, has_ids = inner.uint_slice_arg("ids")
        if not has_n or not n or has_ids or self._single:
            return _reduce(call, partials)
        cand = sorted({p.id for part in partials for p in (part or [])})
        if not cand:
            return []
        refetch = call.clone()
        target = (
            refetch.children[0]
            if refetch.name == "Options" and refetch.children
            else refetch
        )
        target.args["ids"] = cand
        target.args.pop("n", None)
        partials2 = self._map_partials(index_name, idx, refetch, shards)
        merged = _reduce_topn(refetch, partials2)  # no n -> full merge
        return merged[:n]

    def _shard_of_write(self, call: Call) -> int:
        col, ok = call.uint_arg("_col")
        if not ok:
            raise ExecuteError(f"{call.name}() column argument required")
        return col // (self.holder.n_words * 32)

    def _submit_writes(
        self, index_name: str, call: Call, by_node: dict[str, list[int] | None]
    ) -> dict:
        """Launch a write on several nodes CONCURRENTLY (the reference
        fans replica writes from the coordinating goroutine,
        executor.go:2140-2207); the caller overlaps its local apply and
        then collects with ``_collect_writes``."""
        return {
            self._submit(
                self.client.query_node,
                self._node_by_id(node_id).uri,
                index_name,
                str(call),
                nshards if nshards is not None else [],
            ): node_id
            for node_id, nshards in by_node.items()
        }

    def _node_by_id(self, node_id: str):
        """Resolve a node for fan-out, including JOINING nodes: during an
        online resize a flipped shard routes to a pending-ring member
        that is not in ``cluster.nodes`` until the commit lands."""
        n = self.cluster.node(node_id)
        if n is None and self.cluster.pending_nodes is not None:
            for p in self.cluster.pending_nodes:
                if p.id == node_id:
                    return p
        if n is None:
            raise NoAvailableReplicaError(f"unknown fan-out node {node_id}")
        return n

    @staticmethod
    def _collect_writes(futures: dict) -> list[Any]:
        """Remote raw results; any node failure propagates WITH the
        failing node named — synchronous replica writes must not silently
        drop a replica."""
        out = []
        for f in concurrent.futures.as_completed(futures):
            try:
                out.append(decode_results(f.result()["wireResults"])[0])
            except ClientError as e:
                raise ClientError(
                    f"replica write failed on node {futures[f]}: {e}", e.code
                ) from e
        return out

    def _execute_point_write(self, index_name: str, idx, call: Call) -> Any:
        """Apply on every replica of the shard (reference
        executor.go:2140-2207 executeSetBitField)."""
        shard = self._shard_of_write(call)
        remote: dict[str, list[int] | None] = {}
        local = False
        for node in self.cluster.shard_nodes(index_name, shard):
            if node.id == self.cluster.node_id:
                local = True
            else:
                remote[node.id] = [shard]
        futures = self._submit_writes(index_name, call, remote)
        result = self.local._execute_call(idx, call, [shard]) if local else None
        for r in self._collect_writes(futures):
            result = r if result is None else (result or r)
        return result

    def _execute_broadcast_write(self, index_name: str, idx, call: Call) -> Any:
        remote: dict[str, list[int] | None] = {
            n.id: None for n in self.cluster.nodes if n.id != self.cluster.node_id
        }
        futures = self._submit_writes(index_name, call, remote)
        result = self.local._execute_call(idx, call, None)
        self._collect_writes(futures)
        return result

    def _execute_shard_write(
        self, index_name: str, idx, call: Call, shards: list[int]
    ) -> Any:
        """ClearRow/Store on every replica of every shard so replicas
        never diverge (the reference reaches the same end state via
        mapReduce + anti-entropy repair)."""
        by_replica: dict[str, list[int]] = {}
        for s in shards:
            for node in self.cluster.shard_nodes(index_name, s):
                by_replica.setdefault(node.id, []).append(s)
        local_shards = by_replica.pop(self.cluster.node_id, None)
        futures = self._submit_writes(index_name, call, by_replica)
        changed = False
        if local_shards is not None:
            changed |= bool(self.local._execute_call(idx, call, local_shards))
        changed |= any(bool(r) for r in self._collect_writes(futures))
        return changed

    # -- map-reduce (reference executor.go:2454-2611) -----------------------

    def _map_reduce(
        self, index_name: str, idx, call: Call, shards: list[int]
    ) -> Any:
        return _reduce(call, self._map_partials(index_name, idx, call, shards))

    def _map_partials(
        self, index_name: str, idx, call: Call, shards: list[int]
    ) -> list[Any]:
        pql_text = str(call)
        span = tracing.start_span("executor.mapReduce").set_tag("call", call.name)
        span.set_tag("shards", len(shards))
        with span:
            bad_nodes: set[str] = set()
            partials: list[Any] = []
            pending = list(shards)
            while pending:
                # Fail the whole fan-out fast once the request's budget
                # is spent — re-mapping shards onto replicas is pointless
                # work the caller will never see.
                deadline.check(f"mapping {call.name} over {index_name}")
                try:
                    groups = self._group_by_live_owner(
                        index_name, pending, bad_nodes
                    )
                except NoAvailableReplicaError:
                    if not self.cluster.resize_pending:
                        raise
                    # Mid-resize a shard can flip between grouping and
                    # failover: the node that just failed may no longer
                    # be in the (post-flip) owner set at all.  Re-group
                    # once against the current ring with a clean slate.
                    groups = self._group_by_live_owner(
                        index_name, pending, set()
                    )
                pending = []
                # Remote nodes are queried CONCURRENTLY (one pool task per
                # node, the reference's goroutine-per-node mapper,
                # executor.go:2520-2555) while the local shard group runs
                # on the request thread; results are collected in arrival
                # order and failed nodes' shards re-mapped onto remaining
                # replicas for the next loop pass.
                local_shards = groups.pop(self.cluster.node_id, None)
                futures = {
                    self._submit(
                        self._query_remote,
                        self._node_by_id(node_id).uri,
                        node_id,
                        index_name,
                        pql_text,
                        nshards,
                    ): (node_id, nshards)
                    for node_id, nshards in groups.items()
                }
                if local_shards is not None:
                    partials.append(
                        self.local._execute_call(idx, call, local_shards)
                    )
                for fut in concurrent.futures.as_completed(futures):
                    node_id, nshards = futures[fut]
                    try:
                        partials.append(fut.result())
                    except ClientError:
                        # Failover: re-map this node's shards onto remaining
                        # replicas (reference executor.go:2495-2506).
                        bad_nodes.add(node_id)
                        pending.extend(nshards)
            if not partials:
                partials = [self.local._execute_call(idx, call, [])]
            return partials

    def _query_remote(
        self,
        uri: str,
        node_id: str,
        index_name: str,
        pql_text: str,
        shards: list[int],
    ) -> Any:
        """One fan-out leg: remote query plus sub-profile graft.  When the
        coordinator's query is being profiled the remote node returns its
        own profile dict in the response envelope, and we hang it off the
        current span so ``?profile=true`` shows the whole cluster tree."""
        want = qprofile.profiling()
        # real tracing span (not just a profile node): the remote node's
        # http.query span parents to THIS span, so a cluster-assembled
        # trace shows coordinator -> fanout -> peer as one tree
        fanout = tracing.start_span("dist.fanout")
        fanout.set_tag("peer", node_id).set_tag("shards", len(shards))
        with fanout, qprofile.span("fanout", node=node_id, shards=len(shards)):
            resp = self.client.query_node(
                uri, index_name, pql_text, shards, profile=want
            )
            if want:
                qprofile.add_subprofile(node_id, resp.get("profile"))
            return decode_results(resp["wireResults"])[0]

    def _peer_available(self, node) -> bool:
        """Circuit-breaker routing check — local node is always
        available (no transport involved), and a client without breakers
        (NopInternalClient, test doubles) never vetoes a peer."""
        if node.id == self.cluster.node_id:
            return True
        check = getattr(self.client, "peer_available", None)
        if check is None:
            return True
        return check(node.uri)

    def _group_by_live_owner(
        self, index_name: str, shards: list[int], bad_nodes: set[str]
    ) -> dict[str, list[int]]:
        groups: dict[str, list[int]] = {}
        for s in shards:
            owner = None
            fallback = None
            for node in self.cluster.shard_nodes(index_name, s):
                if node.id in bad_nodes or node.state == NODE_STATE_DOWN:
                    continue
                # Two-pass selection: prefer a replica whose circuit
                # breaker admits traffic, so fan-outs route around a
                # flapping peer BEFORE membership confirms it down; if
                # every live replica is tripped, degrade gracefully and
                # use the first anyway (it may have just recovered, and
                # failover still covers us if it hasn't).
                if fallback is None:
                    fallback = node
                if self._peer_available(node):
                    owner = node
                    break
            if owner is None:
                owner = fallback
            if owner is None:
                raise NoAvailableReplicaError(
                    f"no available replica for shard {s} of {index_name!r}"
                )
            groups.setdefault(owner.id, []).append(s)
        return groups


# -- reduce functions (reference executor.go per-call reduceFns) ------------


def _reduce(call: Call, partials: list[Any]) -> Any:
    name = call.name
    if name == "Options" and call.children:
        name = call.children[0].name
    fn = _REDUCERS.get(name)
    if fn is None:
        if name in _BITMAP_CALLS:
            fn = _reduce_rows_union
        else:
            raise ExecuteError(f"no reducer for call {call.name!r}")
    return fn(call, partials)


def _reduce_rows_union(call: Call, partials: list[Any]) -> Row:
    out = Row({})
    for p in partials:
        if p is not None:
            out = out.union(p)
    return out


def _reduce_count(call: Call, partials: list[Any]) -> int:
    return sum(int(p) for p in partials if p is not None)


def _reduce_sum(call: Call, partials: list[Any]) -> ValCount:
    out = ValCount()
    for p in partials:
        if p is not None:
            out = ValCount(out.value + p.value, out.count + p.count)
    return out


def _reduce_min_max(maximal: bool) -> Callable:
    def fn(call: Call, partials: list[Any]) -> ValCount:
        out = None
        for p in partials:
            if p is None or p.count == 0:
                continue
            if out is None or (p.value > out.value) == maximal:
                out = ValCount(p.value, p.count)
            elif p.value == out.value:
                out = ValCount(out.value, out.count + p.count)
        return out or ValCount()

    return fn


def _reduce_min_max_row(maximal: bool) -> Callable:
    def fn(call: Call, partials: list[Any]) -> Pair:
        out = None
        for p in partials:
            if p is None or p.count == 0:
                continue
            if out is None or (p.id > out.id) == maximal:
                out = Pair(id=p.id, key=p.key, count=p.count)
            elif p.id == out.id:
                out = Pair(id=out.id, key=out.key, count=out.count + p.count)
        return out or Pair()

    return fn


def _reduce_topn(call: Call, partials: list[Any]) -> list[Pair]:
    counts: dict[int, int] = {}
    for p in partials:
        for pair in p or []:
            counts[pair.id] = counts.get(pair.id, 0) + pair.count
    n, _ = call.uint_arg("n")
    pairs = sorted(
        (Pair(id=i, count=c) for i, c in counts.items()),
        key=lambda pr: (-pr.count, pr.id),
    )
    if n:
        pairs = pairs[:n]
    return pairs


def _reduce_rows_call(call: Call, partials: list[Any]) -> RowIdentifiers:
    ids: set[int] = set()
    for p in partials:
        if p is not None:
            ids.update(p.rows)
    rows = sorted(ids)
    limit, ok = call.uint_arg("limit")
    if ok and limit is not None:
        rows = rows[:limit]
    return RowIdentifiers(rows=rows)


def _reduce_groupby(call: Call, partials: list[Any]) -> list[GroupCount]:
    merged: dict[tuple, GroupCount] = {}
    for p in partials:
        for gc in p or []:
            key = tuple((g.field, g.row_id, g.row_key) for g in gc.group)
            if key in merged:
                merged[key] = GroupCount(gc.group, merged[key].count + gc.count)
            else:
                merged[key] = GroupCount(gc.group, gc.count)
    out = sorted(
        merged.values(), key=lambda gc: [g.row_id for g in gc.group]
    )
    limit, ok = call.uint_arg("limit")
    if ok and limit is not None:
        out = out[:limit]
    return [gc for gc in out if gc.count > 0]


def _reduce_bool_or(call: Call, partials: list[Any]) -> bool:
    return any(bool(p) for p in partials if p is not None)


def _reduce_first(call: Call, partials: list[Any]) -> Any:
    return partials[0] if partials else None


_REDUCERS: dict[str, Callable] = {
    "Count": _reduce_count,
    "Sum": _reduce_sum,
    "Min": _reduce_min_max(False),
    "Max": _reduce_min_max(True),
    "MinRow": _reduce_min_max_row(False),
    "MaxRow": _reduce_min_max_row(True),
    "TopN": _reduce_topn,
    "Rows": _reduce_rows_call,
    "GroupBy": _reduce_groupby,
    "ClearRow": _reduce_bool_or,
    "Store": _reduce_bool_or,
    "Set": _reduce_bool_or,
    "Clear": _reduce_bool_or,
    "SetRowAttrs": _reduce_first,
    "SetColumnAttrs": _reduce_first,
}
