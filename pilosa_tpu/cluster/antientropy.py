"""Anti-entropy replica repair (reference: holder.go:683-839 holderSyncer,
fragment.go:2849-3011 fragmentSyncer, server.go:494-546 monitorAntiEntropy).

Periodically, each node walks the fragments it owns and reconciles them
with the other replicas:

1. schema sync — pull every peer's schema and apply the union locally
   (the reference exchanges full NodeStatus incl. schema on gossip
   push/pull, gossip/gossip.go:321-357), healing missed broadcasts;
2. per-fragment block sync — fetch 100-row block checksums from each
   replica (fragment.go Blocks), and for every differing block fetch the
   raw (row, col) pairs and merge to consensus: a bit survives when set
   on >= ceil(n/2) replicas, ties keep the bit (fragment.go:1914
   majorityN); each replica then receives exactly its set/clear diff.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.obs import events as ev
from pilosa_tpu.obs import tracing

logger = logging.getLogger(__name__)


class HolderSyncer:
    """reference holder.go:683 holderSyncer."""

    def __init__(self, holder, cluster, client, api):
        self.holder = holder
        self.cluster = cluster
        self.client = client
        self.api = api

    # -- entry point --------------------------------------------------------

    def sync_holder(self) -> dict:
        """One full anti-entropy pass. Returns counters for observability
        (reference SyncHolder holder.go:683)."""
        stats = {
            "fragments": 0, "blocks_diff": 0, "bits_set": 0,
            "bits_cleared": 0, "attrs_merged": 0, "translate_entries": 0,
        }
        job = self.holder.jobs.start("antientropy")
        if len(self.cluster.nodes) <= 1:
            # Single node: the pass is a no-op, but it still counts as a
            # completed round (the loop ran; there was nothing to repair).
            self._finish_round(job, stats, time.monotonic())
            return stats
        t0 = time.monotonic()
        try:
            self._sync_holder(stats, job)
        except Exception as e:
            job.finish("error", error=f"{type(e).__name__}: {e}")
            raise
        self._finish_round(job, stats, t0)
        return stats

    def _finish_round(self, job, stats: dict, t0: float) -> None:
        """Round bookkeeping: summary counters into the stats sink
        (instead of dropping the dict), a journal event, and the job's
        terminal state."""
        hstats = self.holder.stats
        hstats.count("antientropy_rounds", 1)
        hstats.count(
            "antientropy_bits_repaired",
            stats["bits_set"] + stats["bits_cleared"],
        )
        hstats.count("antientropy_blocks_merged", stats["blocks_diff"])
        self.holder.events.record(
            ev.EVENT_ANTIENTROPY_ROUND,
            duration=time.monotonic() - t0,
            job=job.id,
            **stats,
        )
        job.finish("done")

    def _sync_holder(self, stats: dict, job) -> None:
        # span per pass (reference holder.go:683 SyncHolder spans)
        with tracing.start_span("holderSyncer.SyncHolder"):
            # translate-log replication rides the anti-entropy carrier
            # (reference replicas stream continuously, translate.go:91-97;
            # one pull per pass converges replicas the same way)
            translator = (
                self.api.executor.translator if self.api is not None else None
            )
            if translator is not None and hasattr(
                translator, "sync_from_primary"
            ):
                try:
                    stats["translate_entries"] = translator.sync_from_primary()
                except Exception:
                    logger.warning(
                        "translate-log sync failed", exc_info=True
                    )
            job.set_phase("schema")
            self.sync_schema()
            job.set_phase("fragments")
            job.set_progress(fragments_total=self._count_owned_fragments())
            for index_name in list(self.holder.index_names()):
                idx = self.holder.index(index_name)
                if idx is None:
                    continue
                # column attrs (reference holder.go:747-790 syncIndex)
                self.sync_attrs(index_name, None, idx.column_attrs, stats)
                for fname in idx.field_names(include_internal=True):
                    field = idx.field(fname)
                    if field is None:
                        continue
                    # row attrs (reference holder.go:793-839 syncField)
                    self.sync_attrs(index_name, fname, field.row_attrs, stats)
                    for vname in field.view_names():
                        view = field.view(vname)
                        for shard in sorted(view.fragments):
                            if not self.cluster.owns_shard(
                                self.cluster.node_id, index_name, shard
                            ):
                                continue
                            # One bad fragment must not abort the whole
                            # pass — the loop retries next interval anyway.
                            try:
                                self.sync_fragment(
                                    index_name, fname, vname, shard, stats
                                )
                            except Exception as e:
                                logger.warning(
                                    "sync of %s/%s/%s/%d failed: %s",
                                    index_name, fname, vname, shard, e,
                                )
                            stats["fragments"] += 1
                            job.advance(fragments_done=1)
                            job.set_progress(
                                bits_repaired=stats["bits_set"]
                                + stats["bits_cleared"],
                                blocks_merged=stats["blocks_diff"],
                            )

    def _count_owned_fragments(self) -> int:
        """How many fragments this pass will visit (job progress total)."""
        n = 0
        for index_name in list(self.holder.index_names()):
            idx = self.holder.index(index_name)
            if idx is None:
                continue
            for fname in idx.field_names(include_internal=True):
                field = idx.field(fname)
                if field is None:
                    continue
                for vname in field.view_names():
                    view = field.view(vname)
                    for shard in sorted(view.fragments):
                        if self.cluster.owns_shard(
                            self.cluster.node_id, index_name, shard
                        ):
                            n += 1
        return n

    def sync_schema(self) -> None:
        """Apply the union of all peers' schemas locally (missed
        create-index/create-field broadcasts heal here)."""
        for node in self.cluster.nodes:
            if node.id == self.cluster.node_id:
                continue
            try:
                status = self.client.status(node.uri)
            except ClientError:
                continue
            schema = status.get("schema")
            if schema:
                try:
                    self.holder.apply_schema(schema)
                except Exception as e:
                    logger.warning("schema sync from %s failed: %s", node.id, e)
            # shard-availability exchange (reference NodeStatus carries
            # available-shard bitmaps, gossip.go:321-357)
            if status.get("availableShards"):
                self.api.merge_available_shards(status["availableShards"])

    # -- attr sync (reference holder.go:747-839 syncIndex/syncField) --------

    def sync_attrs(self, index: str, field: str | None, store, stats: dict) -> None:
        """Pull-merge attribute blocks that differ from any peer. Attrs
        replicate to every node at write time (broadcast writes); each
        node's pass pulls blocks it is missing, so all converge without a
        push path (the reference does the same via AttrStore diffs)."""
        local = {bid: chk.hex() for bid, chk in store.blocks()}
        for node in self.cluster.nodes:
            if node.id == self.cluster.node_id:
                continue
            try:
                remote = {
                    b["id"]: b["checksum"]
                    for b in self.client.attr_blocks(node.uri, index, field)
                }
            except ClientError:
                continue
            for bid, chk in remote.items():
                if local.get(bid) == chk:
                    continue
                try:
                    attrs = self.client.attr_block_data(
                        node.uri, index, field, bid
                    )
                except ClientError as e:
                    logger.warning(
                        "attr block fetch from %s failed: %s", node.id, e
                    )
                    continue
                if attrs:
                    store.set_bulk_attrs(attrs)
                    stats["attrs_merged"] += len(attrs)
            # refresh local checksums after merging this peer
            local = {bid: chk.hex() for bid, chk in store.blocks()}

    # -- fragment sync (reference fragment.go:2849 syncFragment) ------------

    def sync_fragment(
        self, index: str, field: str, view: str, shard: int, stats: dict
    ) -> None:
        replicas = [
            n
            for n in self.cluster.shard_nodes(index, shard)
            if n.id != self.cluster.node_id
        ]
        if not replicas:
            return
        frag = self.holder.fragment(index, field, view, shard)
        if frag is None:
            return
        local_blocks = {b["id"]: b["checksum"] for b in frag.blocks()}
        # Union of block ids that differ from ANY replica.
        remote_blocks: dict[str, dict[int, str]] = {}
        diff_ids: set[int] = set()
        for node in replicas:
            try:
                blocks = self.client.fragment_blocks(
                    node.uri, index, field, view, shard
                )
                rb = {b["id"]: b["checksum"] for b in blocks}
            except ClientError as e:
                if e.code == 404:
                    rb = {}  # replica has no fragment yet: all blocks differ
                else:
                    logger.warning("blocks fetch from %s failed: %s", node.id, e)
                    continue
            remote_blocks[node.id] = rb
            for bid in set(local_blocks) | set(rb):
                if local_blocks.get(bid) != rb.get(bid):
                    diff_ids.add(bid)
        for bid in sorted(diff_ids):
            stats["blocks_diff"] += 1
            self._merge_block(
                index, field, view, shard, bid, frag, replicas,
                remote_blocks, stats, local_blocks.get(bid),
            )

    def _merge_block(
        self, index, field, view, shard, block, frag, replicas,
        remote_blocks, stats, local_sum=None,
    ) -> None:
        """Majority-consensus merge of one block (reference
        fragment.go:1873-1991 mergeBlock + syncBlock :2900-3011)."""
        pair_sets: dict[str, set[tuple[int, int]]] = {}
        lrows, lcols = frag.block_data(block)
        local_pairs = set(zip(lrows, lcols))
        pair_sets[self.cluster.node_id] = local_pairs
        for node in replicas:
            if node.id not in remote_blocks:
                continue  # unreachable earlier; skip from consensus
            # Matching checksum ⇒ identical pair set; skip the fetch
            # (only blocks differing from SOME replica reach here).
            if local_sum is not None and remote_blocks[node.id].get(block) == local_sum:
                pair_sets[node.id] = local_pairs
                continue
            try:
                data = self.client.block_data(
                    node.uri, index, field, view, shard, block,
                    width=frag.shard_width,
                )
                pair_sets[node.id] = set(zip(data["rows"], data["cols"]))
            except ClientError as e:
                if e.code == 404:
                    pair_sets[node.id] = set()
                else:
                    logger.warning("block data from %s failed: %s", node.id, e)
        n = len(pair_sets)
        if n <= 1:
            return
        majority = (n + 1) // 2  # ties keep the bit (fragment.go:1914)
        counts: dict[tuple[int, int], int] = {}
        for pairs in pair_sets.values():
            for p in pairs:
                counts[p] = counts.get(p, 0) + 1
        keep = {p for p, c in counts.items() if c >= majority}
        # Per-replica diffs: sets = keep - have, clears = have - keep.
        for node_id, have in pair_sets.items():
            to_set = keep - have
            to_clear = have - keep
            if not to_set and not to_clear:
                continue
            if node_id == self.cluster.node_id:
                self._apply_local(frag, to_set, to_clear)
                stats["bits_set"] += len(to_set)
                stats["bits_cleared"] += len(to_clear)
            else:
                node = self.cluster.node(node_id)
                # count only bits actually shipped (the wire format may
                # drop unencodable rows)
                n_set, n_clear = self._push_remote(
                    node, index, field, view, shard, frag, to_set, to_clear
                )
                stats["bits_set"] += n_set
                stats["bits_cleared"] += n_clear

    def _apply_local(self, frag, to_set, to_clear) -> None:
        if to_set:
            rows = np.array([r for r, _ in to_set], dtype=np.uint64)
            cols = np.array([c for _, c in to_set], dtype=np.int64)
            frag.import_bits(rows, cols)
        if to_clear:
            rows = np.array([r for r, _ in to_clear], dtype=np.uint64)
            cols = np.array([c for _, c in to_clear], dtype=np.int64)
            frag.import_bits(rows, cols, clear=True)

    def _push_remote(
        self, node, index, field, view, shard, frag, to_set, to_clear
    ) -> tuple[int, int]:
        """Ship diffs as roaring batches (the reference pushes syncs
        through ImportRoaring too, fragment.go:2975-3011). Returns the
        (set, clear) counts actually shipped."""
        from pilosa_tpu.storage import roaring

        width = frag.shard_width
        # The wire format is uint64 positions (row*width + col), so rows
        # beyond 2^64/width are unrepresentable — skip them rather than
        # abort the pass (arbitrary uint64 row ids are legal locally).
        max_row = (2**64 - 1 - (width - 1)) // width
        shipped = [0, 0]
        try:
            for i, (pairs, clear) in enumerate(((to_set, False), (to_clear, True))):
                if not pairs:
                    continue
                encodable = [(r, c) for r, c in pairs if r <= max_row]
                if len(encodable) != len(pairs):
                    logger.warning(
                        "skipping %d bits with row ids too large for the "
                        "position wire format", len(pairs) - len(encodable),
                    )
                if not encodable:
                    continue
                positions = np.sort(
                    np.array(
                        [r * width + c for r, c in encodable], dtype=np.uint64
                    )
                )
                self.client.import_roaring(
                    node.uri, index, field, shard,
                    roaring.serialize(positions), clear=clear, view=view,
                )
                shipped[i] = len(encodable)
        except ClientError as e:
            logger.warning("sync push to %s failed: %s", node.id, e)
        return shipped[0], shipped[1]


class AntiEntropyLoop:
    """Background interval loop (reference server.go:494-546).

    ``state_fn`` (when given) gates each pass: only RESIZING/STARTING
    skip — DEGRADED deliberately still syncs, because repair between
    the surviving replicas matters MOST during an outage (the
    reference's monitorAntiEntropy skips only resizing)."""

    _SKIP_STATES = ("RESIZING", "STARTING")

    def __init__(self, syncer: HolderSyncer, interval: float, state_fn=None):
        self.syncer = syncer
        self.interval = interval
        self.state_fn = state_fn
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="pilosa-antientropy", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            if (
                self.state_fn is not None
                and self.state_fn() in self._SKIP_STATES
            ):
                continue
            t0 = time.monotonic()
            try:
                self.syncer.sync_holder()
                # duration metric (reference server.go:532)
                self.syncer.holder.stats.timing(
                    "anti_entropy", time.monotonic() - t0
                )
            except Exception as e:
                logger.warning("anti-entropy pass failed: %s", e)

    def stop(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
