"""Cluster state machine + shard placement (reference: cluster.go).

States and transitions follow cluster.go:46-51 (STARTING / NORMAL /
DEGRADED / RESIZING) with `determine_state` mirroring
determineClusterState (cluster.go:547-558): losing fewer than ReplicaN
nodes degrades reads; losing ReplicaN or more makes data unavailable and
drops the cluster back to STARTING.

Placement is the two-level hash of hash.py. All placement methods are
pure functions of the sorted node list, so every member computes the same
answers without coordination (the reference relies on the same property,
cluster.go:858-934).
"""

from __future__ import annotations

import threading

from pilosa_tpu.cluster.hash import jump_hash, partition_hash
from pilosa_tpu.cluster.topology import NODE_STATE_DOWN, NODE_STATE_READY, Node

STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_DEGRADED = "DEGRADED"
STATE_RESIZING = "RESIZING"

DEFAULT_PARTITION_N = 256  # reference cluster.go:44
DEFAULT_REPLICA_N = 1  # reference cluster.go:237


class Cluster:
    """Membership + placement + state (reference cluster.go:178 cluster)."""

    def __init__(
        self,
        node_id: str,
        uri: str = "",
        replica_n: int = DEFAULT_REPLICA_N,
        partition_n: int = DEFAULT_PARTITION_N,
        coordinator_id: str | None = None,
        disabled: bool = True,
    ):
        self._lock = threading.RLock()
        self.node_id = node_id
        self.replica_n = max(1, replica_n)
        self.partition_n = partition_n
        # disabled=True is the reference's Cluster.Disabled static mode
        # (cluster.go:204, setStatic :2000): membership fixed at boot, no
        # join/leave protocol.
        self.disabled = disabled
        self.coordinator_id = coordinator_id or node_id
        self.state = STATE_NORMAL if disabled else STATE_STARTING
        self.nodes: list[Node] = [
            Node(id=node_id, uri=uri, is_coordinator=(self.coordinator_id == node_id))
        ]
        self.on_state_change = None  # hook: fn(new_state)
        # In-flight online resize: while a migration runs the cluster
        # keeps serving from ``nodes``, but shards whose transfer has
        # completed flip — one (index, shard) at a time — onto the
        # ``pending_nodes`` placement.  ``epoch`` is a monotonic fence:
        # every flip/commit/abort bumps it, so a node can reject stale
        # flip broadcasts from an aborted resize generation.
        self.pending_nodes: list[Node] | None = None
        self.flipped: set[tuple[str, int]] = set()
        self.epoch = 0

    # -- membership ---------------------------------------------------------

    def node(self, node_id: str) -> Node | None:
        for n in self.nodes:
            if n.id == node_id:
                return n
        return None

    @property
    def local_node(self) -> Node:
        n = self.node(self.node_id)
        assert n is not None
        return n

    @property
    def is_coordinator(self) -> bool:
        return self.node_id == self.coordinator_id

    def add_node(self, node: Node) -> None:
        """Insert keeping the list sorted by id (placement stability)."""
        with self._lock:
            if self.node(node.id) is not None:
                return
            node.is_coordinator = node.id == self.coordinator_id
            self.nodes.append(node)
            self.nodes.sort()

    def remove_node(self, node_id: str) -> bool:
        with self._lock:
            n = self.node(node_id)
            if n is None:
                return False
            self.nodes.remove(n)
            return True

    def set_static(self, nodes: list[Node]) -> None:
        """Fix membership at boot (reference setStatic cluster.go:2000).
        Also the resize-commit landing point: committing a membership
        resolves any in-flight per-shard flip state."""
        with self._lock:
            self.nodes = sorted(nodes, key=lambda n: n.id)
            for n in self.nodes:
                n.is_coordinator = n.id == self.coordinator_id
            if self.pending_nodes is not None:
                self.pending_nodes = None
                self.flipped = set()
                self.epoch += 1
            changed = self.state != STATE_NORMAL
            self.state = STATE_NORMAL
        # The implicit RESIZING->NORMAL edge of a membership commit must
        # reach the observer hook like any explicit set_state call.
        if changed and self.on_state_change is not None:
            self.on_state_change(STATE_NORMAL)

    # -- online resize (per-shard flips instead of a cluster-wide gate) -----

    def begin_resize(self, pending_nodes: list[Node], epoch: int | None = None) -> int:
        """Arm an in-flight resize: placement stays on the current
        membership until individual shards flip.  Returns the new epoch
        (the coordinator broadcasts it; followers pass it back in so
        every node agrees on the fence value)."""
        with self._lock:
            # A re-prepare on the SAME epoch is a coordinator resuming an
            # interrupted resize: shards it already flipped must stay
            # flipped, or routing would snap back to the old ring while
            # the targets already drained their sessions.
            same = (
                self.pending_nodes is not None
                and epoch is not None
                and epoch == self.epoch
            )
            self.pending_nodes = sorted(pending_nodes, key=lambda n: n.id)
            if not same:
                self.flipped = set()
            self.epoch = epoch if epoch is not None else self.epoch + 1
            return self.epoch

    def flip_shard(self, index: str, shard: int, epoch: int | None = None) -> bool:
        """Move one shard's placement onto the pending membership.
        Rejected (returns False) when no resize is armed or the flip
        rides a stale epoch — a crashed-and-aborted resize generation
        must not flip shards of a later one."""
        with self._lock:
            if self.pending_nodes is None:
                return False
            if epoch is not None and epoch != self.epoch:
                return False
            self.flipped.add((index, int(shard)))
            return True

    def abort_resize(self) -> None:
        """Drop the pending membership: every shard — flipped or not —
        goes back to the current placement (the data still lives there;
        targets only ever held copies until commit)."""
        with self._lock:
            if self.pending_nodes is None:
                return
            self.pending_nodes = None
            self.flipped = set()
            self.epoch += 1

    @property
    def resize_pending(self) -> bool:
        return self.pending_nodes is not None

    # -- state machine ------------------------------------------------------

    def set_state(self, state: str) -> None:
        with self._lock:
            if state == self.state:
                return
            self.state = state
        if self.on_state_change is not None:
            self.on_state_change(state)

    def determine_state(self) -> str:
        """reference determineClusterState cluster.go:547-558."""
        with self._lock:
            down = sum(1 for n in self.nodes if n.state == NODE_STATE_DOWN)
            if down == 0:
                return STATE_NORMAL
            if down < self.replica_n:
                return STATE_DEGRADED
            return STATE_STARTING

    def mark_node_state(self, node_id: str, state: str) -> None:
        n = self.node(node_id)
        if n is not None:
            n.state = state
        if self.state != STATE_RESIZING:
            self.set_state(self.determine_state())

    # -- placement (reference cluster.go:847-934) ---------------------------

    def partition(self, index: str, shard: int) -> int:
        return partition_hash(index, shard, self.partition_n)

    def _ring_nodes(self, ring: list[Node], partition_id: int) -> list[Node]:
        n = len(ring)
        if n == 0:
            return []
        primary = jump_hash(partition_id, n)
        count = min(self.replica_n, n)
        return [ring[(primary + i) % n] for i in range(count)]

    def partition_nodes(self, partition_id: int) -> list[Node]:
        """Primary + replicas for a partition: jump-hash picks the primary
        ordinal; ReplicaN consecutive ring nodes follow (reference
        cluster.go:878-898)."""
        with self._lock:
            return self._ring_nodes(self.nodes, partition_id)

    def shard_nodes(self, index: str, shard: int) -> list[Node]:
        """Owner set for one shard — the single point every read/write
        route derives from.  During an online resize a flipped shard
        resolves over the pending membership, so routing follows each
        per-shard ownership flip the moment it lands, with no
        cluster-wide gate."""
        with self._lock:
            ring = self.nodes
            if self.pending_nodes is not None and (index, int(shard)) in self.flipped:
                ring = self.pending_nodes
            return self._ring_nodes(ring, self.partition(index, shard))

    def primary_shard_node(self, index: str, shard: int) -> Node:
        return self.shard_nodes(index, shard)[0]

    def owns_shard(self, node_id: str, index: str, shard: int) -> bool:
        return any(n.id == node_id for n in self.shard_nodes(index, shard))

    def owned_shards(self, node_id: str, index: str, shards) -> list[int]:
        return [s for s in shards if self.owns_shard(node_id, index, s)]

    def shards_by_node(self, index: str, shards) -> dict[str, list[int]]:
        """Primary-owner grouping for query fan-out (reference
        shardsByNode executor.go:2438)."""
        out: dict[str, list[int]] = {}
        for s in shards:
            out.setdefault(self.primary_shard_node(index, s).id, []).append(s)
        return out

    def translate_primary(self) -> Node | None:
        """Key-translation primary = the coordinator's node in this build.

        (The reference uses the previous ring node, cluster.go:1971-1996;
        with a static sorted membership the coordinator is an equivalent
        deterministic, well-known choice.)"""
        return self.node(self.coordinator_id)

    # -- status -------------------------------------------------------------

    def nodes_info(self) -> list[dict]:
        with self._lock:
            return [n.to_dict() for n in self.nodes]

    def status(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "replicaN": self.replica_n,
                "partitionN": self.partition_n,
                "coordinator": self.coordinator_id,
                "nodes": [n.to_dict() for n in self.nodes],
                "epoch": self.epoch,
                "resizePending": self.pending_nodes is not None,
                "flippedShards": len(self.flipped),
            }
