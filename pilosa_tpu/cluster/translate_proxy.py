"""Primary/replica key translation (reference: translate.go:91-97,
cluster.go:1971-1996, holder.go:643-650).

The reference designates one node as translation primary; replicas
stream its append-only log and refuse new-key writes
(ErrTranslateStoreReadOnly, translate.go:52). Here non-primary nodes
forward new-key allocation to the primary over HTTP and cache the
returned mappings in their local store, so id→key result translation is
local after first use and replicas never allocate conflicting ids.
"""

from __future__ import annotations

from pilosa_tpu.core.translate import TranslateStore


class PrimaryTranslateStore:
    """TranslateStore facade routing allocation to the cluster's
    translation primary."""

    def __init__(self, local: TranslateStore, cluster, client):
        self.local = local
        self.cluster = cluster
        self.client = client
        # replication cursor into the primary's entry log (reference
        # translate.go:91-97 log-position streaming)
        self._log_offset = 0

    def _is_primary(self) -> bool:
        primary = self.cluster.translate_primary()
        return (
            primary is None
            or primary.id == self.cluster.node_id
            or len(self.cluster.nodes) <= 1
        )

    def translate_keys(self, index: str, field: str, keys: list[str], create: bool = True) -> list[int]:
        if self._is_primary():
            return self.local.translate_keys(index, field, keys, create=create)
        # Serve fully-cached batches locally; otherwise ask the primary.
        cached = self.local.translate_keys(index, field, keys, create=False)
        if all(i != 0 for i in cached):
            return cached
        primary = self.cluster.translate_primary()
        ids = self.client.translate_keys(primary.uri, index, field or "", keys)
        self.local.set_mapping(index, field, keys, ids)
        return ids

    def translate_ids(self, index: str, field: str, id_list: list[int]) -> list[str]:
        out = self.local.translate_ids(index, field, id_list)
        if all(k != "" for k in out) or self._is_primary():
            return out
        primary = self.cluster.translate_primary()
        keys = self.client.translate_ids(primary.uri, index, field or "", id_list)
        # set_mapping drops ""-keyed entries, so unknown ids are re-asked
        # rather than cached as poison.
        self.local.set_mapping(index, field, keys, id_list)
        return keys

    def sync_from_primary(self) -> int:
        """Pull the primary's entry log since our cursor and apply it
        locally; returns the number of entries applied (the reference's
        replica log streaming, translate.go:91-97; carried here by the
        anti-entropy loop).  After a full sync every ids->keys read is
        local, the local ``.keys`` log holds a complete copy (set_mapping
        fires on_insert for each new entry), and this node can take over
        as primary with full state.  A restarted primary re-feeds its
        log from a possibly different offset base, so the cursor resets
        whenever it runs past the primary's log length."""
        if self._is_primary():
            return 0
        primary = self.cluster.translate_primary()
        applied = 0
        while True:
            entries, new_offset, log_len = self.client.translate_log(
                primary.uri, self._log_offset
            )
            if self._log_offset > log_len:
                # primary restarted with a shorter log: restart the feed
                # (applies are idempotent)
                self._log_offset = 0
                continue
            if not entries:
                return applied
            # batch contiguous (index, field) runs — one set_mapping
            # (and one on_insert disk append) per run, not per key,
            # matching the replay path's batching (translatelog.py)
            run: tuple[str, str] | None = None
            keys: list[str] = []
            ids: list[int] = []
            for index, field, key, id_ in entries:
                if (index, field) != run:
                    if run is not None:
                        self.local.set_mapping(run[0], run[1], keys, ids)
                    run = (index, field)
                    keys, ids = [], []
                keys.append(key)
                ids.append(id_)
            if run is not None:
                self.local.set_mapping(run[0], run[1], keys, ids)
            applied += len(entries)
            self._log_offset = new_offset

    def translate_key(self, index: str, field: str, key: str, create: bool = True) -> int:
        return self.translate_keys(index, field, [key], create=create)[0]

    def translate_id(self, index: str, field: str, id_: int) -> str:
        return self.translate_ids(index, field, [id_])[0]

    def to_dict(self) -> dict:
        return self.local.to_dict()

    def load_dict(self, d: dict) -> None:
        self.local.load_dict(d)
