"""Primary/replica key translation (reference: translate.go:91-97,
cluster.go:1971-1996, holder.go:643-650).

The reference designates one node as translation primary; replicas
stream its append-only log and refuse new-key writes
(ErrTranslateStoreReadOnly, translate.go:52). Here non-primary nodes
forward new-key allocation to the primary over HTTP and cache the
returned mappings in their local store, so id→key result translation is
local after first use and replicas never allocate conflicting ids.
"""

from __future__ import annotations

from pilosa_tpu.core.translate import TranslateStore


class PrimaryTranslateStore:
    """TranslateStore facade routing allocation to the cluster's
    translation primary."""

    def __init__(self, local: TranslateStore, cluster, client):
        self.local = local
        self.cluster = cluster
        self.client = client

    def _is_primary(self) -> bool:
        primary = self.cluster.translate_primary()
        return (
            primary is None
            or primary.id == self.cluster.node_id
            or len(self.cluster.nodes) <= 1
        )

    def translate_keys(self, index: str, field: str, keys: list[str], create: bool = True) -> list[int]:
        if self._is_primary():
            return self.local.translate_keys(index, field, keys, create=create)
        # Serve fully-cached batches locally; otherwise ask the primary.
        cached = self.local.translate_keys(index, field, keys, create=False)
        if all(i != 0 for i in cached):
            return cached
        primary = self.cluster.translate_primary()
        ids = self.client.translate_keys(primary.uri, index, field or "", keys)
        self.local.set_mapping(index, field, keys, ids)
        return ids

    def translate_ids(self, index: str, field: str, id_list: list[int]) -> list[str]:
        out = self.local.translate_ids(index, field, id_list)
        if all(k != "" for k in out) or self._is_primary():
            return out
        primary = self.cluster.translate_primary()
        keys = self.client.translate_ids(primary.uri, index, field or "", id_list)
        # set_mapping drops ""-keyed entries, so unknown ids are re-asked
        # rather than cached as poison.
        self.local.set_mapping(index, field, keys, id_list)
        return keys

    def translate_key(self, index: str, field: str, key: str, create: bool = True) -> int:
        return self.translate_keys(index, field, [key], create=create)[0]

    def translate_id(self, index: str, field: str, id_: int) -> str:
        return self.translate_ids(index, field, [id_])[0]

    def to_dict(self) -> dict:
        return self.local.to_dict()

    def load_dict(self, d: dict) -> None:
        self.local.load_dict(d)
