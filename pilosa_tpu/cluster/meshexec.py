"""Mesh-local collective execution: serve peer nodes' shards straight
from the local device mesh.

``DistributedExecutor`` used to relay EVERY non-local shard group over
HTTP, even when the owner node's fragments live in this process and are
slices of the same serving mesh (``parallel/mesh.py``).  This module
provides the read-only holder facade that makes those shards executable
locally: a ``MeshHolderView`` presents the union of the coordinator's
holder and each mesh-local owner's holder, restricted to the shard
assignment the placement plan computed, so a plain ``exec.Executor``
built over the facade answers the whole mesh partition as ONE
jit-sharded launch — stacked ``[S, R, W]`` tensors over the mesh's
``("shards",)`` axis, psum/all-gather style reductions inside the
kernels — with no sockets involved.

The facade is strictly read-only: writes never reach it
(``cluster/dist.py`` routes every write call through its replica-aware
paths before mesh planning happens), so none of the mutating holder /
index / field methods are proxied.

Identity matters for performance: the executor's field-stack caches live
in ``vars(field)`` keyed per field object, so ``MeshIndex`` memoizes its
``MeshField`` facades (and ``dist`` memoizes whole facade executors per
shard assignment) to keep warm stacks across queries.  Delegation of
public attributes falls through to the coordinator's own objects;
underscore-prefixed attributes are deliberately NOT delegated so the
executor's per-field cache slots (``_stack_caches`` et al.) stay private
to the facade and can never alias the base field's caches.
"""

from __future__ import annotations

import threading

from pilosa_tpu.core.index import EXISTENCE_FIELD_NAME


class MeshView:
    """A view whose fragments resolve, per shard, to the ASSIGNED owner
    node's live fragment objects."""

    def __init__(self, name: str, owners: list[tuple]):
        # owners: [(real View, shards assigned to that owner), ...]
        self.name = name
        self._owners = owners
        self._view_by_shard = {s: v for v, sh in owners for s in sh}

    @property
    def fragments(self) -> dict:
        out = {}
        for s, v in self._view_by_shard.items():
            frag = v.fragments.get(s)
            if frag is not None:
                out[s] = frag
        return out

    def fragment(self, shard: int):
        v = self._view_by_shard.get(shard)
        return None if v is None else v.fragments.get(shard)

    def available_shards(self) -> set[int]:
        return {
            s for s, v in self._view_by_shard.items() if s in v.fragments
        }

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._owners[0][0], name)


class MeshField:
    def __init__(self, base, owners: list[tuple]):
        # owners: [(real Field, shards assigned to that owner), ...] —
        # includes the coordinator's own field when it owns shards.
        self._base = base
        self._owners = owners

    def view(self, name: str) -> MeshView | None:
        got = [
            (v, sh)
            for v, sh in ((f.view(name), sh) for f, sh in self._owners)
            if v is not None
        ]
        if not got:
            return None
        return MeshView(name, got)

    @property
    def views(self) -> dict:
        names = {n for f, _ in self._owners for n in f.views}
        return {n: self.view(n) for n in sorted(names)}

    def view_names(self) -> list[str]:
        return sorted({n for f, _ in self._owners for n in f.views})

    def available_shards(self) -> set[int]:
        out: set[int] = set()
        for f, sh in self._owners:
            for v in f.views.values():
                out |= v.available_shards() & set(sh)
        return out

    def __getattr__(self, name: str):
        # Never delegate private attributes: the executor parks its
        # stack caches/locks in vars(field), and falling through to the
        # base field here would silently share (and corrupt) them.
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._base, name)


class MeshIndex:
    def __init__(self, base, owners: list[tuple]):
        # owners: [(real Index, shards assigned to that owner), ...]
        self._base = base
        self._owners = owners
        self._field_cache: dict[str, MeshField] = {}
        self._lock = threading.Lock()

    def field(self, name: str) -> MeshField | None:
        base_f = self._base.field(name)
        if base_f is None:
            return None
        with self._lock:
            mf = self._field_cache.get(name)
            if mf is not None and mf._base is base_f:
                return mf
        fowners = []
        complete = True
        for ix, sh in self._owners:
            f = ix.field(name)
            if f is None:
                # schema broadcast still in flight on that owner — serve
                # an uncached facade so the next call re-checks
                complete = False
            else:
                fowners.append((f, sh))
        mf = MeshField(base_f, fowners)
        if complete:
            with self._lock:
                self._field_cache[name] = mf
        return mf

    def existence_field(self) -> MeshField | None:
        return self.field(EXISTENCE_FIELD_NAME)

    @property
    def fields(self) -> dict:
        return {n: self.field(n) for n in list(self._base.fields)}

    def available_shards(self) -> set[int]:
        out: set[int] = set()
        for ix, sh in self._owners:
            out |= ix.available_shards() & set(sh)
        return out

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._base, name)


class MeshHolderView:
    """Read-only holder facade over a mesh partition.

    ``owners`` maps node id -> (holder, shards) for every node in the
    partition, INCLUDING the coordinator itself when it owns shards —
    folding the local group into the facade is what turns local + peer
    work into a single launch.
    """

    def __init__(self, base, owners: dict):
        self._base = base
        self._owners = owners
        self._index_cache: dict[str, MeshIndex] = {}
        self._lock = threading.Lock()

    def index(self, name: str) -> MeshIndex | None:
        base_idx = self._base.index(name)
        if base_idx is None:
            return None
        with self._lock:
            mi = self._index_cache.get(name)
            if mi is not None and mi._base is base_idx:
                return mi
        iowners = []
        complete = True
        for nid in sorted(self._owners):
            holder, shards = self._owners[nid]
            ix = holder.index(name)
            if ix is None:
                complete = False
            else:
                iowners.append((ix, frozenset(shards)))
        mi = MeshIndex(base_idx, iowners)
        if complete:
            with self._lock:
                self._index_cache[name] = mi
        return mi

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._base, name)
