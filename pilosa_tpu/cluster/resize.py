"""Online cluster resize (reference: cluster.go:687-844 fragSources /
fragsDiff, :1038-1536 resizeJob / followResizeInstruction).

TPU meshes are static, so within one process resize never happens — this
implements the reference's *cluster-level* elasticity: adding or removing
a host re-runs jump-hash placement over the new membership and moves only
the fragments whose owner set changed (jump consistent hashing guarantees
that set is minimal).

Unlike the reference (which gates the whole cluster to RESIZING and 503s
every write until the transfer finishes), this resize is **online**: the
cluster state stays NORMAL end to end and ownership moves one shard at a
time behind per-fragment migration:

1. **prepare** — every member (old + joining) learns the PENDING
   membership and the resize epoch (MSG_RESIZE_PREPARE).  Placement
   stays on the current ring; an unreachable *surviving* member aborts
   the resize here (committing a membership it never heard of would
   strand it on the old ring).
2. **inventory** — which old member holds which fragments (reference
   fragsByHost cluster.go:687).  Removing an unreachable node surfaces
   any un-replicated fragments as a journaled ``resize-data-loss`` event
   plus a ``resize_data_loss_fragments`` counter — never silently.
3. **migrate, per shard group** — each new owner pulls the shard's
   fragments from a live holder: snapshot cut streamed in resumable
   chunks, then bounded op-log catch-up rounds while writes keep landing
   on the current owner (server/api.py migrate_fetch; source half in
   cluster/migration.py).
4. **flip** — one broadcast moves that shard's placement onto the
   pending ring (MSG_EPOCH_FLIP, fenced by the epoch).  Reads were
   replica-served throughout; writes start routing to the new owner.
5. **finalize** — the new owners drain the final op-log delta the flip
   raced with and close their source sessions.
6. **commit** — full membership + shard map to every node
   (MSG_CLUSTER_STATUS; reference mergeClusterStatus), and each node
   drops fragments it no longer owns (reference holderCleaner).

Every phase is crash-survivable: the plan persists as a resize journal
(``resize.json`` in the data dir, mirrored in-process for storeless
clusters) before any state moves, progress is checkpointed per shard
group, and ``resume()`` re-dispatches idempotently from the journal — a
coordinator that dies mid-migrate leaves a resumable plan, not a wedged
cluster.  ``testing/faults.py`` crash rules fire at every
``coordinator:*`` stage boundary below.
"""

from __future__ import annotations

import json
import logging
import os
import time

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.cluster.cluster import Cluster, STATE_NORMAL
from pilosa_tpu.cluster.topology import Node
from pilosa_tpu.obs import events as ev
from pilosa_tpu.testing import faults

logger = logging.getLogger(__name__)

JOURNAL_FILE = "resize.json"


class ResizeError(Exception):
    pass


class ResizeCoordinator:
    """Runs on the coordinator node (reference: only the coordinator
    generates resize jobs, cluster.go:1171)."""

    def __init__(self, cluster: Cluster, client, api):
        self.cluster = cluster
        self.client = client
        self.api = api

    # -- public entry points ------------------------------------------------

    def add_node(self, node_id: str, uri: str) -> None:
        if self.cluster.node(node_id) is not None:
            return
        new_nodes = [
            Node(id=n.id, uri=n.uri) for n in self.cluster.nodes
        ] + [Node(id=node_id, uri=uri)]
        self._resize(sorted(new_nodes, key=lambda n: n.id))

    def remove_node(self, node_id: str) -> None:
        if self.cluster.node(node_id) is None:
            raise ResizeError(f"node not in cluster: {node_id}")
        if node_id == self.cluster.node_id:
            raise ResizeError("coordinator cannot remove itself")
        new_nodes = [
            Node(id=n.id, uri=n.uri)
            for n in self.cluster.nodes
            if n.id != node_id
        ]
        if not new_nodes:
            raise ResizeError("cannot remove the last node")
        self._resize(new_nodes, removed=node_id)

    def resume(self) -> dict:
        """Re-dispatch an interrupted resize from the persisted journal.
        Completed shard groups are skipped (checkpointed per group);
        re-dispatching the rest is idempotent — snapshot applies are
        set-merges and delta replay follows file order."""
        plan = self._load_journal()
        if plan is None:
            raise ResizeError("no interrupted resize to resume")
        new_nodes = [Node(id=d["id"], uri=d["uri"]) for d in plan["nodes"]]
        self.api.holder.events.record(
            ev.EVENT_RESIZE_RESUME,
            action=plan.get("action"),
            epoch=plan.get("epoch"),
            done=len(plan.get("done") or []),
        )
        self._resize(new_nodes, removed=plan.get("removed"), resume_plan=plan)
        return {"resumed": True, "members": [n.id for n in new_nodes]}

    # -- the job ------------------------------------------------------------

    def _resize(
        self,
        new_nodes: list[Node],
        removed: str | None = None,
        resume_plan: dict | None = None,
    ) -> None:
        if self.cluster.resize_pending and resume_plan is None:
            raise ResizeError(
                "a resize is already in flight; resume or abort it first"
            )
        old_nodes = list(self.cluster.nodes)
        all_nodes = {n.id: n for n in old_nodes}
        for n in new_nodes:
            all_nodes.setdefault(n.id, n)
        epoch = (
            int(resume_plan["epoch"]) if resume_plan
            else self.cluster.epoch + 1
        )
        plan = resume_plan or {
            "action": "remove" if removed else "add",
            "removed": removed,
            "epoch": epoch,
            "nodes": [{"id": n.id, "uri": n.uri} for n in new_nodes],
            "done": [],
        }
        done: set[str] = set(plan.get("done") or [])
        # Persist BEFORE any cluster state moves: from here on a
        # coordinator death leaves a resumable plan, not a mystery.
        self._write_journal(plan)

        journal = self.api.holder.events
        job = self.api.holder.jobs.start(
            "resize",
            action=plan["action"],
            old_nodes=len(old_nodes),
            new_nodes=len(new_nodes),
            epoch=epoch,
            resumed=bool(resume_plan),
        )
        journal.record(
            ev.EVENT_RESIZE_START,
            action=plan["action"],
            old=[n.id for n in old_nodes],
            new=[n.id for n in new_nodes],
            removed=removed,
            epoch=epoch,
            job=job.id,
        )
        try:
            # 1. prepare: pending membership + epoch everywhere.  The
            # cluster state stays NORMAL — no read/write gate.
            job.set_phase("prepare")
            journal.record(
                ev.EVENT_RESIZE_PHASE, phase="prepare", job=job.id,
            )
            faults.stage_fault("coordinator:prepare")
            self._send_prepare(all_nodes.values(), new_nodes, epoch, removed)
            if resume_plan is not None:
                # Nodes that restarted since the crash lost their flip
                # state; re-broadcasting completed flips is idempotent.
                for key in sorted(done):
                    index, shard = key.rsplit(":", 1)
                    self._broadcast_flip(
                        all_nodes.values(), index, int(shard), epoch
                    )
            # 2. inventory: which old member holds which fragments.
            job.set_phase("inventory")
            journal.record(
                ev.EVENT_RESIZE_PHASE, phase="inventory", job=job.id,
            )
            holders = self._gather_inventory(old_nodes, exclude=removed)
            # 3. placement under the new membership -> per-shard plan.
            new_cluster = Cluster(
                self.cluster.node_id,
                replica_n=self.cluster.replica_n,
                partition_n=self.cluster.partition_n,
                coordinator_id=self.cluster.coordinator_id,
            )
            new_cluster.set_static(
                [Node(id=n.id, uri=n.uri) for n in new_nodes]
            )
            old_ids = {n.id for n in old_nodes}
            joining = [n for n in new_nodes if n.id not in old_ids]
            groups = self._plan_groups(
                holders, new_cluster, all_nodes, removed
            )
            total = sum(
                len(ins)
                for by_target in groups.values()
                for ins in by_target.values()
            )
            job.set_phase("migrate")
            job.set_progress(
                fragments_total=total, shards_total=len(groups),
            )
            journal.record(
                ev.EVENT_RESIZE_PHASE, phase="migrate", job=job.id,
                shards=len(groups), fragments=total,
            )
            faults.stage_fault("coordinator:migrate")
            # Joining nodes need the schema before any fragment lands
            # (reference cluster.go:1304-1323); idempotent on resume.
            schema = self.api.holder.schema()
            for n in joining:
                self._dispatch(
                    n, "migrate_fetch",
                    {"instructions": [], "schema": schema},
                )
            # 4. per shard group: fetch -> flip -> finalize.  Reads are
            # replica-served throughout; writes follow the flip.
            for group_key in sorted(groups):
                index, shard = group_key
                key_str = f"{index}:{shard}"
                if key_str in done:
                    continue
                by_target = groups[group_key]
                for tid, instructions in by_target.items():
                    self._dispatch(
                        all_nodes[tid], "migrate_fetch",
                        {"instructions": instructions},
                    )
                faults.stage_fault("coordinator:flip")
                self._broadcast_flip(
                    all_nodes.values(), index, shard, epoch
                )
                journal.record(
                    ev.EVENT_MIGRATE_FRAGMENT,
                    index=index, shard=shard, epoch=epoch,
                    targets=sorted(by_target),
                    fragments=sum(len(i) for i in by_target.values()),
                    job=job.id,
                )
                for tid, instructions in by_target.items():
                    self._dispatch(
                        all_nodes[tid], "migrate_finalize",
                        {"instructions": instructions},
                    )
                job.advance(
                    shards_done=1,
                    fragments_done=sum(
                        len(i) for i in by_target.values()
                    ),
                )
                done.add(key_str)
                plan["done"] = sorted(done)
                self._write_journal(plan)  # checkpoint per shard group
        except faults.CrashError:
            # Simulated coordinator death: no abort, no cleanup — the
            # journal stays on disk and resume() picks the plan back up.
            job.finish("aborted", error="coordinator crash (injected)")
            raise
        except Exception as e:
            journal.record(
                ev.EVENT_RESIZE_ABORT, job=job.id,
                error=f"{type(e).__name__}: {e}",
            )
            job.finish("aborted", error=f"{type(e).__name__}: {e}")
            self._cancel(all_nodes.values(), f"{type(e).__name__}: {e}")
            self._delete_journal()
            raise
        # 5. commit: new membership + NORMAL everywhere, then cleanup.
        # The commit carries the global shard-availability map so every
        # node re-learns which shards exist cluster-wide (local holdings
        # changed; stale remote sets would shrink query fan-out).
        faults.stage_fault("coordinator:commit")
        shard_map: dict = {}
        for (index, field, _view, shard) in holders:
            shard_map.setdefault(index, {}).setdefault(field, set()).add(shard)
        shard_map = {
            i: {f: sorted(s) for f, s in fields.items()}
            for i, fields in shard_map.items()
        }
        job.set_phase("commit")
        journal.record(ev.EVENT_RESIZE_PHASE, phase="commit", job=job.id)
        self._commit_membership(all_nodes.values(), new_nodes, shard_map)
        journal.record(
            ev.EVENT_RESIZE_COMMIT, job=job.id, epoch=epoch,
            members=[n.id for n in new_nodes],
        )
        self._delete_journal()
        job.finish("done")

    # -- planning -----------------------------------------------------------

    def _plan_groups(
        self, holders: dict, new_cluster: Cluster, all_nodes: dict,
        removed: str | None,
    ) -> dict[tuple, dict[str, list[dict]]]:
        """(index, shard) -> {target node id -> fetch instructions}.
        Each instruction lists EVERY live holder as a source (staying
        members first, a gracefully-leaving node last) so the target can
        fail over mid-pull."""
        groups: dict[tuple, dict[str, list[dict]]] = {}
        for frag_key, holder_ids in holders.items():
            index, field, view, shard = frag_key
            src_uris = [
                all_nodes[h].uri for h in holder_ids if h != removed
            ]
            if removed in holder_ids:
                src_uris.append(all_nodes[removed].uri)
            for target in new_cluster.shard_nodes(index, shard):
                if target.id in holder_ids:
                    continue
                if not src_uris:
                    raise ResizeError(
                        f"no live source for fragment {frag_key}"
                    )
                groups.setdefault((index, int(shard)), {}).setdefault(
                    target.id, []
                ).append(
                    {
                        "index": index,
                        "field": field,
                        "view": view,
                        "shard": int(shard),
                        "sourceURIs": src_uris,
                    }
                )
        return groups

    # -- fan-out helpers ----------------------------------------------------

    def _dispatch(self, target: Node, method: str, req: dict):
        if target.id == self.cluster.node_id:
            return getattr(self.api, method)(req)
        return getattr(self.client, method)(target.uri, req)

    def _send_prepare(
        self, nodes, new_nodes: list[Node], epoch: int,
        removed: str | None,
    ) -> None:
        msg = {
            "type": bc.MSG_RESIZE_PREPARE,
            "epoch": epoch,
            "nodes": [{"id": n.id, "uri": n.uri} for n in new_nodes],
        }
        for n in nodes:
            if n.id == self.cluster.node_id:
                self.api.receive_message(msg)
                continue
            try:
                self.client.send_message(n.uri, msg)
            except ClientError as e:
                if n.id == removed:
                    # Removing a dead node IS the recovery path; its
                    # missing ack must not block the resize.
                    logger.warning(
                        "prepare to leaving node %s failed: %s", n.id, e
                    )
                    continue
                # A SURVIVING member that never hears the prepare would
                # keep routing on the old ring after the commit — abort
                # instead of carrying on with a warning.
                raise ResizeError(
                    f"prepare fan-out to surviving member {n.id} "
                    f"failed: {e}"
                )

    def _broadcast_flip(
        self, nodes, index: str, shard: int, epoch: int
    ) -> None:
        msg = {
            "type": bc.MSG_EPOCH_FLIP,
            "index": index,
            "shard": int(shard),
            "epoch": epoch,
        }
        for n in nodes:
            if n.id == self.cluster.node_id:
                self.api.receive_message(msg)
                continue
            try:
                self.client.send_message(n.uri, msg)
            except ClientError as e:
                # Best-effort: a node that misses a flip keeps routing
                # this shard to the old owner — reads stay correct (the
                # source holds the fragment until commit cleanup) and
                # the commit converges membership for good.
                logger.warning("flip fan-out to %s failed: %s", n.id, e)

    def _cancel(self, nodes, reason: str) -> None:
        """Broadcast a resize cancel: every node drops its pending
        membership and flip state; placement snaps back to the current
        ring (where the data still lives)."""
        msg = {"type": bc.MSG_RESIZE_CANCEL, "reason": reason}
        for n in nodes:
            if n.id == self.cluster.node_id:
                self.api.receive_message(msg)
                continue
            try:
                self.client.send_message(n.uri, msg)
            except ClientError as e:
                logger.warning("resize-cancel to %s failed: %s", n.id, e)

    def _gather_inventory(
        self, old_nodes, exclude: str | None
    ) -> dict[tuple, list[str]]:
        """fragment key -> node ids actually holding it (reference
        fragsByHost cluster.go:687)."""
        holders: dict[tuple, list[str]] = {}
        dead: list[str] = []
        for n in old_nodes:
            if n.id == self.cluster.node_id:
                frags = self.api.fragment_inventory()
            else:
                try:
                    frags = self.client.fragment_list(n.uri)
                except ClientError as e:
                    if exclude is not None and n.id == exclude:
                        dead.append(n.id)
                        continue
                    raise ResizeError(
                        f"inventory fetch from {n.id} failed: {e}"
                    )
            for fr in frags:
                key = (fr["index"], fr["field"], fr["view"], fr["shard"])
                holders.setdefault(key, []).append(n.id)
        if dead:
            self._journal_data_loss(dead[0], holders)
        return holders

    def _journal_data_loss(self, node_id: str, holders: dict) -> None:
        """Removing an unreachable node can lose its un-replicated
        fragments: anything the cluster-wide shard-availability map says
        exists but no SURVIVING member holds.  Surface it loudly — a
        journaled event plus a /metrics counter — instead of silently
        skipping the dead node's inventory."""
        known = self.api.available_shards_map()
        held = {(i, f, int(s)) for (i, f, _v, s) in holders}
        lost = []
        for index, fields in known.items():
            for field, shards in fields.items():
                for s in shards:
                    if (index, field, int(s)) not in held:
                        lost.append((index, field, int(s)))
        if not lost:
            return
        self.api.holder.events.record(
            ev.EVENT_RESIZE_DATA_LOSS,
            node=node_id,
            count=len(lost),
            fragments=[list(k) for k in lost[:32]],
        )
        self.api.holder.stats.count(
            "resize_data_loss_fragments", len(lost)
        )
        logger.error(
            "resize removed dead node %s: %d un-replicated fragment(s)"
            " lost", node_id, len(lost),
        )

    # -- resize journal (crash-survivable plan) -----------------------------

    def _journal_path(self) -> str | None:
        store = self.api.store
        if store is None or not getattr(store, "path", None):
            return None
        return os.path.join(store.path, JOURNAL_FILE)

    def _write_journal(self, plan: dict) -> None:
        self.api._resize_journal = plan
        path = self._journal_path()
        if path is None:
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(plan, f)
        os.replace(tmp, path)  # atomic: a crash mid-write keeps the old plan

    def _load_journal(self) -> dict | None:
        plan = getattr(self.api, "_resize_journal", None)
        if plan is not None:
            return plan
        path = self._journal_path()
        if path is None or not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            logger.error("unreadable resize journal %s: %s", path, e)
            return None

    def _delete_journal(self) -> None:
        self.api._resize_journal = None
        path = self._journal_path()
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass

    # -- commit -------------------------------------------------------------

    def _commit_membership(
        self, all_nodes, members: list[Node], shard_map: dict | None = None
    ) -> None:
        status = {
            "type": bc.MSG_CLUSTER_STATUS,
            "state": STATE_NORMAL,
            "coordinator": self.cluster.coordinator_id,
            "nodes": [{"id": n.id, "uri": n.uri} for n in members],
        }
        if shard_map:
            status["availableShards"] = shard_map
        member_ids = {n.id for n in members}
        # First sweep: one attempt per node, so a slow/dead node can't
        # head-of-line-block healthy members' commit.
        retry: list = []
        for n in all_nodes:
            if n.id == self.cluster.node_id:
                self.api.receive_message(status)
                continue
            try:
                self.client.send_message(n.uri, status)
            except ClientError:
                # A removed node that is already gone is expected; a
                # surviving member missing the commit keeps routing on
                # the pre-resize ring (its watchdog re-pulls status from
                # the coordinator as the backstop), so retry below.
                if n.id in member_ids:
                    retry.append(n)
        for n in retry:
            for attempt in range(4):
                try:
                    self.client.send_message(n.uri, status)
                    break
                except ClientError as e:
                    if attempt < 3:
                        time.sleep(0.2 * 2**attempt)
                    else:
                        logger.error(
                            "commit to %s failed after %d attempts: %s "
                            "(its resize watchdog re-pulls the cluster "
                            "status from the coordinator to recover)",
                            n.id, attempt + 2, e,
                        )
