"""Elastic cluster resize (reference: cluster.go:687-844 fragSources /
fragsDiff, :1038-1536 resizeJob / followResizeInstruction).

TPU meshes are static, so within one process resize never happens — this
implements the reference's *cluster-level* elasticity: adding or removing
a host re-runs jump-hash placement over the new membership and moves only
the fragments whose owner set changed (jump consistent hashing guarantees
that set is minimal).

Flow, coordinator-driven exactly like the reference (one membership
change at a time, cluster.go:1038):

1. coordinator broadcasts RESIZING (API gates to fragment-transfer-only,
   api.go:100-124);
2. it gathers the global fragment inventory from every old member,
   computes, per NEW member, the fragments that member will own under the
   new placement but does not hold, each with a source node that does
   (reference fragSources);
3. each member synchronously fetches its missing fragments from the
   sources (reference followResizeInstruction streams fragment archives);
4. coordinator commits the new membership + NORMAL state to every member
   (reference mergeClusterStatus), and each drops fragments it no longer
   owns (reference holderCleaner, holder.go:898-926).

On failure the coordinator broadcasts an abort: old membership + NORMAL
(reference ResizeAbort api.go:1249).
"""

from __future__ import annotations

import logging
import time

from pilosa_tpu.cluster import broadcast as bc
from pilosa_tpu.cluster.client import ClientError
from pilosa_tpu.cluster.cluster import (
    Cluster,
    STATE_NORMAL,
    STATE_RESIZING,
)
from pilosa_tpu.cluster.topology import Node
from pilosa_tpu.obs import events as ev

logger = logging.getLogger(__name__)


class ResizeError(Exception):
    pass


class ResizeCoordinator:
    """Runs on the coordinator node (reference: only the coordinator
    generates resize jobs, cluster.go:1171)."""

    def __init__(self, cluster: Cluster, client, api):
        self.cluster = cluster
        self.client = client
        self.api = api

    # -- public entry points ------------------------------------------------

    def add_node(self, node_id: str, uri: str) -> None:
        if self.cluster.node(node_id) is not None:
            return
        new_nodes = [
            Node(id=n.id, uri=n.uri) for n in self.cluster.nodes
        ] + [Node(id=node_id, uri=uri)]
        self._resize(sorted(new_nodes))

    def remove_node(self, node_id: str) -> None:
        if self.cluster.node(node_id) is None:
            raise ResizeError(f"node not in cluster: {node_id}")
        if node_id == self.cluster.node_id:
            raise ResizeError("coordinator cannot remove itself")
        new_nodes = [
            Node(id=n.id, uri=n.uri)
            for n in self.cluster.nodes
            if n.id != node_id
        ]
        if not new_nodes:
            raise ResizeError("cannot remove the last node")
        self._resize(new_nodes, removed=node_id)

    # -- the job ------------------------------------------------------------

    def _resize(self, new_nodes: list[Node], removed: str | None = None) -> None:
        old_nodes = list(self.cluster.nodes)
        all_nodes = {n.id: n for n in old_nodes}
        for n in new_nodes:
            all_nodes.setdefault(n.id, n)

        journal = self.api.holder.events
        job = self.api.holder.jobs.start(
            "resize",
            action="remove" if removed else "add",
            old_nodes=len(old_nodes),
            new_nodes=len(new_nodes),
        )
        journal.record(
            ev.EVENT_RESIZE_START,
            action="remove" if removed else "add",
            old=[n.id for n in old_nodes],
            new=[n.id for n in new_nodes],
            removed=removed,
            job=job.id,
        )
        try:
            # 1. everyone (old + joining) enters RESIZING.
            job.set_phase("broadcast-resizing")
            journal.record(ev.EVENT_RESIZE_PHASE, phase="broadcast-resizing", job=job.id)
            self._send_state_everywhere(all_nodes.values(), STATE_RESIZING)
            # 2. inventory: which old member holds which fragments.
            job.set_phase("inventory")
            journal.record(ev.EVENT_RESIZE_PHASE, phase="inventory", job=job.id)
            holders = self._gather_inventory(old_nodes, exclude=removed)
            # 3. placement under the new membership.
            new_cluster = Cluster(
                self.cluster.node_id,
                replica_n=self.cluster.replica_n,
                partition_n=self.cluster.partition_n,
                coordinator_id=self.cluster.coordinator_id,
            )
            new_cluster.set_static([Node(id=n.id, uri=n.uri) for n in new_nodes])
            # 4. per new member: fetch instructions for missing fragments.
            old_ids = {n.id for n in old_nodes}
            plan: list[tuple[Node, list[dict], bool]] = []
            for target in new_nodes:
                is_joining = target.id not in old_ids
                instructions = []
                for frag_key, holder_ids in holders.items():
                    index, field, view, shard = frag_key
                    if not new_cluster.owns_shard(target.id, index, shard):
                        continue
                    if target.id in holder_ids:
                        continue
                    # Prefer a staying holder; a gracefully-leaving node
                    # still serves as source (the reference streams from
                    # the leaving node on removal).
                    source = next(
                        (all_nodes[h] for h in holder_ids if h != removed),
                        all_nodes[removed] if removed in holder_ids else None,
                    )
                    if source is None:
                        raise ResizeError(
                            f"no live source for fragment {frag_key}"
                        )
                    instructions.append(
                        {
                            "index": index,
                            "field": field,
                            "view": view,
                            "shard": shard,
                            "sourceURI": source.uri,
                        }
                    )
                if instructions or is_joining:
                    plan.append((target, instructions, is_joining))
            job.set_phase("migrate")
            job.set_progress(
                fragments_total=sum(len(ins) for _, ins, _ in plan)
            )
            journal.record(
                ev.EVENT_RESIZE_PHASE, phase="migrate", job=job.id,
                targets=len(plan),
                fragments=sum(len(ins) for _, ins, _ in plan),
            )
            for target, instructions, is_joining in plan:
                # Joining nodes get the schema first (reference
                # followResizeInstruction applies schema before any
                # fragment transfer, cluster.go:1304-1323).
                self._dispatch_fetch(target, instructions, is_joining)
                job.advance(fragments_done=len(instructions))
        except Exception as e:
            # Abort: restore old membership + NORMAL on every reachable
            # node (reference ResizeAbort).
            journal.record(
                ev.EVENT_RESIZE_ABORT, job=job.id,
                error=f"{type(e).__name__}: {e}",
            )
            job.finish("aborted", error=f"{type(e).__name__}: {e}")
            self._commit_membership(all_nodes.values(), old_nodes)
            raise
        # 5. commit: new membership + NORMAL everywhere, then cleanup.
        # The commit carries the global shard-availability map so every
        # node re-learns which shards exist cluster-wide (local holdings
        # changed; stale remote sets would shrink query fan-out).
        shard_map: dict = {}
        for (index, field, _view, shard) in holders:
            shard_map.setdefault(index, {}).setdefault(field, set()).add(shard)
        shard_map = {
            i: {f: sorted(s) for f, s in fields.items()}
            for i, fields in shard_map.items()
        }
        job.set_phase("commit")
        journal.record(ev.EVENT_RESIZE_PHASE, phase="commit", job=job.id)
        self._commit_membership(all_nodes.values(), new_nodes, shard_map)
        journal.record(
            ev.EVENT_RESIZE_COMMIT, job=job.id,
            members=[n.id for n in new_nodes],
        )
        job.finish("done")

    def _send_state_everywhere(self, nodes, state: str) -> None:
        for n in nodes:
            if n.id == self.cluster.node_id:
                self.cluster.set_state(state)
            else:
                try:
                    self.client.send_message(
                        n.uri, {"type": bc.MSG_CLUSTER_STATUS, "state": state}
                    )
                except ClientError as e:
                    logger.warning("state fan-out to %s failed: %s", n.id, e)

    def _gather_inventory(
        self, old_nodes, exclude: str | None
    ) -> dict[tuple, list[str]]:
        """fragment key -> node ids actually holding it (reference
        fragsByHost cluster.go:687)."""
        holders: dict[tuple, list[str]] = {}
        for n in old_nodes:
            if n.id == self.cluster.node_id:
                frags = self.api.fragment_inventory()
            else:
                try:
                    frags = self.client.fragment_list(n.uri)
                except ClientError as e:
                    if exclude is not None and n.id == exclude:
                        continue  # removing a dead node: its data is lost
                    raise ResizeError(
                        f"inventory fetch from {n.id} failed: {e}"
                    )
            for fr in frags:
                key = (fr["index"], fr["field"], fr["view"], fr["shard"])
                holders.setdefault(key, []).append(n.id)
        return holders

    def _dispatch_fetch(
        self, target: Node, instructions: list[dict], with_schema: bool = False
    ) -> None:
        req: dict = {"instructions": instructions}
        if with_schema:
            req["schema"] = self.api.holder.schema()
        if target.id == self.cluster.node_id:
            self.api.resize_fetch(req)
        else:
            self.client.resize_fetch(target.uri, req)

    def _commit_membership(
        self, all_nodes, members: list[Node], shard_map: dict | None = None
    ) -> None:
        status = {
            "type": bc.MSG_CLUSTER_STATUS,
            "state": STATE_NORMAL,
            "coordinator": self.cluster.coordinator_id,
            "nodes": [{"id": n.id, "uri": n.uri} for n in members],
        }
        if shard_map:
            status["availableShards"] = shard_map
        member_ids = {n.id for n in members}
        # First sweep: one attempt per node, so a slow/dead node can't
        # head-of-line-block healthy members' exit from RESIZING.
        retry: list = []
        for n in all_nodes:
            if n.id == self.cluster.node_id:
                self.api.receive_message(status)
                continue
            try:
                self.client.send_message(n.uri, status)
            except ClientError:
                # A removed node that is already gone is expected; a
                # surviving member missing the commit would be stuck in
                # RESIZING forever (503 on all traffic), so retry below.
                if n.id in member_ids:
                    retry.append(n)
        for n in retry:
            for attempt in range(4):
                try:
                    self.client.send_message(n.uri, status)
                    break
                except ClientError as e:
                    if attempt < 3:
                        time.sleep(0.2 * 2**attempt)
                    else:
                        logger.error(
                            "commit to %s failed after %d attempts: %s "
                            "(node left in RESIZING; re-send the cluster "
                            "status or restart it to recover)",
                            n.id, attempt + 2, e,
                        )
