"""Placement hashing (reference: cluster.go:847-934).

Two layers, exactly as in the reference:

1. (index, shard) -> partition: FNV-1a over the index name plus the
   big-endian shard id, mod partitionN (reference cluster.go:847-856).
2. partition -> node ordinal: Lamping-Veach jump consistent hash
   (reference cluster.go:922-934 ``jmphasher``), which moves a minimal
   set of partitions when the node count changes.

Both are deterministic pure functions so every node computes identical
placement with no coordination.
"""

from __future__ import annotations

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a64(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK64
    return h


def partition_hash(index: str, shard: int, partition_n: int) -> int:
    """Hash (index, shard) onto a partition id (reference
    cluster.go:847-856)."""
    data = index.encode() + shard.to_bytes(8, "big")
    return fnv1a64(data) % partition_n


def jump_hash(key: int, n_buckets: int) -> int:
    """Jump consistent hash (Lamping & Veach 2014; reference
    cluster.go:922-934). Maps a 64-bit key onto [0, n_buckets) such that
    growing n_buckets relocates only ~1/n of keys."""
    if n_buckets <= 0:
        raise ValueError("n_buckets must be positive")
    b, j = -1, 0
    key &= _MASK64
    while j < n_buckets:
        b = j
        key = (key * 2862933555777941757 + 1) & _MASK64
        j = int((b + 1) * (1 << 31) / ((key >> 33) + 1))
    return b
